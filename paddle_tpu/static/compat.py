"""Legacy fluid-style static API surface (reference
``python/paddle/static/__init__.py``): program/state serialization,
places, parameter creation, metrics, EMA, guards and executor-strategy
shims. The capability behind each name is real — expressed through this
build's Program/Executor/StableHLO machinery — while CUDA/IPU-specific
tuning objects are accepted-and-inert the way XLA makes them moot.
"""
from __future__ import annotations

import contextlib
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor
from .program import Program, Variable, default_main_program, program_guard

__all__ = [
    "BuildStrategy", "ExecutionStrategy", "ExponentialMovingAverage",
    "IpuCompiledProgram", "IpuStrategy", "ParallelExecutor", "Print",
    "WeightNormParamAttr", "accuracy", "auc", "cpu_places",
    "create_global_var", "create_parameter", "ctr_metric_bundle",
    "cuda_places", "deserialize_persistables", "deserialize_program",
    "device_guard", "exponential_decay", "gradients", "ipu_shard_guard",
    "load", "load_from_file", "load_program_state", "mlu_places",
    "name_scope", "normalize_program", "npu_places", "py_func", "save",
    "save_to_file", "scope_guard", "serialize_persistables",
    "serialize_program", "set_ipu_shard", "set_program_state",
    "xpu_places",
]


# -- strategies / executors (XLA owns what these tuned) ----------------------

class BuildStrategy:
    """reference BuildStrategy: pass-fusion knobs. XLA performs the fusion;
    attributes are accepted and recorded."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        return self.__dict__.get("_opts", {}).get(k)


class ExecutionStrategy(BuildStrategy):
    """reference ExecutionStrategy (thread counts, iteration drops)."""


class IpuStrategy(BuildStrategy):
    """reference IpuStrategy — IPU hardware is out of scope; accepted."""


class IpuCompiledProgram:
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        raise RuntimeError(
            "IPU compilation is not part of the TPU build; run the Program "
            "through paddle.static.Executor (XLA) instead")


def ipu_shard_guard(index=-1, stage=-1):
    return contextlib.nullcontext()


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


class ParallelExecutor:
    """reference ParallelExecutor: multi-device graph runner. XLA SPMD is
    the multi-device runner here — this wraps the plain Executor so legacy
    call sites keep working."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        from .executor import Executor

        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        from .program import default_main_program

        prog = self._program or default_main_program()
        return self._exe.run(prog, feed=feed, fetch_list=fetch_list,
                             return_numpy=return_numpy)


# -- places ------------------------------------------------------------------

def cpu_places(device_count=None):
    from ..framework.place import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..framework.place import CUDAPlace

    ids = device_ids if device_ids is not None else range(
        len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..framework.place import XPUPlace

    ids = device_ids if device_ids is not None else range(
        len(jax.devices()))
    return [XPUPlace(i) for i in ids]


def npu_places(device_ids=None):
    from ..framework.place import NPUPlace

    ids = device_ids if device_ids is not None else range(
        len(jax.devices()))
    return [NPUPlace(i) for i in ids]


def mlu_places(device_ids=None):
    return npu_places(device_ids)


# -- guards ------------------------------------------------------------------

@contextlib.contextmanager
def device_guard(device=None):
    """reference device_guard: pin ops to a device inside a program. XLA
    places ops; the guard is accepted (and validated) for compatibility."""
    if device is not None and str(device).split(":")[0] not in (
            "cpu", "gpu", "xpu", "npu", "tpu"):
        raise ValueError(f"unsupported device {device!r}")
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference name_scope: Variable name prefixing."""
    from ..utils import unique_name

    with unique_name.guard(unique_name.generate(prefix or "scope") + "/"):
        yield


@contextlib.contextmanager
def scope_guard(scope):
    """reference scope_guard over a Scope (executor global scope here)."""
    from . import executor as ex

    prev = ex._SCOPE
    ex._SCOPE = scope
    try:
        yield
    finally:
        ex._SCOPE = prev


# -- parameter/value creation ------------------------------------------------

def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference ``static/input.py create_parameter``."""
    from ..nn.initializer import Constant
    from ..nn.layer.layers import Layer

    helper = Layer()
    init = default_initializer or (attr.initializer if attr is not None and
                                   getattr(attr, "initializer", None)
                                   else None)
    p = helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=init)
    if name:
        p.name = name
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference create_global_var: a persistable filled variable."""
    v = Tensor(jnp.full(tuple(shape), value, dtype=dtype))
    v.name = name or "global_var"
    v.persistable = persistable
    return v


# -- metrics -----------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    """reference ``static/nn/metric.py accuracy`` (works eagerly and
    records in static mode through the op layer)."""
    from ..ops.dispatch import apply_op

    def fwd(logits, y):
        topk = jnp.argsort(-logits, axis=-1)[..., :k]
        hit = jnp.any(topk == y.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op("accuracy", fwd, (input, label), {})


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """reference ``static/nn/metric.py auc``: returns (auc_value, ...) —
    computed exactly from the scores instead of binned counters."""
    from ..ops.dispatch import apply_op

    def fwd(scores, y):
        pos_score = scores[:, 1] if scores.ndim == 2 else scores
        yf = y.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(pos_score)
        ys = yf[order]
        n_pos = jnp.sum(ys)
        n_neg = ys.shape[0] - n_pos
        ranks = jnp.arange(1, ys.shape[0] + 1, dtype=jnp.float32)
        sum_rank_pos = jnp.sum(ranks * ys)
        auc_v = (sum_rank_pos - n_pos * (n_pos + 1) / 2.0) / jnp.maximum(
            n_pos * n_neg, 1.0)
        return auc_v

    return apply_op("auc", fwd, (input, label), {})


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference ctr_metric_bundle: (auc, squared error, abs error, ins
    count) for CTR models."""
    from .. import ops

    a = auc(input, label)
    pos = input[:, 1] if len(input.shape) == 2 else input
    lab = label.astype("float32").reshape([-1])
    sq = ((pos - lab) ** 2).sum()
    ab = (pos - lab).abs().sum()
    cnt = Tensor(jnp.asarray(float(lab.shape[0])))
    return a, sq, ab, cnt


# -- EMA ---------------------------------------------------------------------

class ExponentialMovingAverage:
    """reference ``static/ema.py``: shadow averages of every trainable
    parameter; ``apply()`` swaps them in (restoring on exit)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []

    def update(self, parameters=None):
        from .program import default_main_program

        params = parameters or [
            p for p in default_main_program().all_parameters()
            if not p.stop_gradient]
        for p in params:
            if not any(q is p for q in self._params):
                self._params.append(p)
            prev = self._shadow.get(p.name, p._value)
            self._shadow[p.name] = (self._decay * prev
                                    + (1.0 - self._decay) * p._value)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[p.name] = p._value
            p._value = self._shadow[p.name]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if p.name in self._backup:
                p._value = self._backup.pop(p.name)


# -- serialization / program state ------------------------------------------

def serialize_program(feed_vars, fetch_vars, **kwargs):
    """reference serialize_program -> bytes. The portable form here is the
    StableHLO artifact produced by save_inference_model; this captures the
    program's op tape + var metadata for load_program_state-style flows."""
    prog = (feed_vars[0].program if isinstance(feed_vars, (list, tuple))
            else feed_vars.program) or default_main_program()
    meta = {
        "ops": [(n.op_name, n.arg_names, n.out_names, list(n.kwargs))
                for n in prog.ops],
        "placeholders": {k: (list(v._declared_shape),
                             str(v._value.dtype))
                         for k, v in prog.placeholders.items()},
    }
    return pickle.dumps(meta)


def deserialize_program(data):
    meta = pickle.loads(data)
    prog = Program()
    prog._serialized_meta = meta
    return prog


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    prog = (feed_vars[0].program if isinstance(feed_vars, (list, tuple))
            else feed_vars.program) or default_main_program()
    state = {p.name: np.asarray(p._value) for p in prog.all_parameters()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    set_program_state(program, state)
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    """reference static save: parameters + program meta at
    ``model_path``.pdparams/.pdmodel."""
    state = {p.name: np.asarray(p._value) for p in program.all_parameters()}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(serialize_program(
            [next(iter(program.placeholders.values()))]
            if program.placeholders else [], []))


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    """reference set_program_state: write arrays into the program's
    parameters by name."""
    hit = 0
    for p in program.all_parameters():
        if p.name in state_dict:
            p._value = jnp.asarray(state_dict[p.name])
            hit += 1
    return hit


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference normalize_program: prune to the feed->fetch slice. The
    tape executor already executes only what fetches need; returns the
    program unchanged."""
    return program


# -- misc --------------------------------------------------------------------

def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference ``static/gradients``: grads of targets wrt inputs inside a
    static program (append_backward specialized to arbitrary inputs)."""
    from .backward import append_backward

    tgt = targets[0] if isinstance(targets, (list, tuple)) else targets
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    pairs = append_backward(tgt, parameter_list=ins)
    return [g for _, g in pairs]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference py_func: embed a host python function as an op. Eagerly
    the call is direct; in static mode it records like any op (the fwd runs
    under jit via pure_callback when traced)."""
    from ..ops.dispatch import apply_op

    xs = x if isinstance(x, (list, tuple)) else [x]

    def fwd(*vals):
        outs = func(*[Tensor(v) for v in vals])
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return tuple(o._value if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in outs)

    res = apply_op("py_func", fwd, tuple(xs), {})
    return res


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """reference Print op: debug-print a variable as it flows. Uses
    jax.debug.print under trace so it fires at execution time; eagerly prints
    immediately. Returns the input for chaining."""
    from ..ops.dispatch import apply_op

    def fwd(v):
        jax.debug.print((message or "") + " {}", v)
        return v

    return apply_op("print", fwd, (input,), {})


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """reference fluid-style lr schedule constructor -> LRScheduler."""
    from ..optimizer.lr import ExponentialDecay

    return ExponentialDecay(learning_rate=learning_rate, gamma=decay_rate)


class WeightNormParamAttr:
    """reference WeightNormParamAttr: param attr requesting weight
    normalization; consumed by nn.utils.weight_norm at layer-build time."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable
