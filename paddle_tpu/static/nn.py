"""static.nn — graph-building layer functions + structured control flow.

Reference: ``python/paddle/static/nn/`` (fc, control_flow cond/while_loop —
C++ twins ``operators/controlflow/conditional_block_op`` and ``while_op``).
cond/while_loop lower directly to ``lax.cond`` / ``lax.while_loop`` so they
work BOTH eagerly (dygraph Tensors, inside to_static traces) and while
recording a static Program.
"""
from __future__ import annotations

import contextlib
import types

import jax
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["fc", "cond", "while_loop", "switch_case"]


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Reference static.nn.fc: flatten trailing dims then affine."""
    from ..nn.layer.common import Linear
    import paddle_tpu.nn.functional as F
    import numpy as np

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        x = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    y = layer(x)
    if activation:
        y = getattr(F, activation)(y)
    return y


def _wrap_branch(fn):
    """Adapt a user branch fn over Tensors to raw arrays for lax."""

    def run(operands):
        t_ops = [Tensor(o) for o in operands]
        out = fn(*t_ops) if t_ops else fn()
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    return run


def np_value(x):
    return x._value if isinstance(x, Tensor) else x


def _closure_tensors(*fns):
    """Tensors (incl. static Variables and Layer parameters) captured in the
    branch functions' closures — the reference discovers conditional-block
    inputs the same way, by scanning the sub-block's referenced vars."""
    from ..nn.layer.layers import Layer

    seen, out = set(), []

    def add(v, depth=0):
        if depth > 4 or id(v) in seen:
            return
        if isinstance(v, Tensor):
            seen.add(id(v))
            out.append(v)
        elif isinstance(v, Layer):
            seen.add(id(v))
            for q in v.parameters():
                add(q, depth)
        elif isinstance(v, (list, tuple, set)):
            seen.add(id(v))
            for q in v:
                add(q, depth + 1)
        elif isinstance(v, dict):
            seen.add(id(v))
            for q in v.values():
                add(q, depth + 1)
        elif getattr(v, "__self__", None) is not None:
            # bound method: scan the receiver (a Layer holding params, say)
            add(v.__self__, depth + 1)
        elif isinstance(v, types.FunctionType):
            # nested closure (e.g. dy2static branch wrappers close over the
            # user's branch fn, which closes over the tensors)
            seen.add(id(v))
            for cell in (v.__closure__ or ()):
                try:
                    add(cell.cell_contents, depth + 1)
                except ValueError:
                    pass
            for d in (v.__defaults__ or ()):
                add(d, depth + 1)

    for fn in fns:
        for cell in (getattr(fn, "__closure__", None) or ()):
            try:
                add(cell.cell_contents)
            except ValueError:
                pass
        for d in (getattr(fn, "__defaults__", None) or ()):
            add(d)
    return out


@contextlib.contextmanager
def _install(tensors, values):
    old = [t._value for t in tensors]
    for t, v in zip(tensors, values):
        t._value = v
    try:
        yield
    finally:
        for t, o in zip(tensors, old):
            t._value = o


@contextlib.contextmanager
def _no_record():
    """Suspend static recording while a control-flow body is traced (its
    inner ops execute on tracers inside the lowered lax region)."""
    from ..ops import dispatch

    prev = dispatch.STATIC_RECORDER
    dispatch.STATIC_RECORDER = None
    try:
        yield
    finally:
        dispatch.STATIC_RECORDER = prev


def _concrete_bool(pred):
    """Python truth value of pred when it is NOT symbolic/traced, else None."""
    from ..static.program import Variable
    from ..framework.tensor import _is_tracer

    if isinstance(pred, Variable):
        return None
    v = pred._value if isinstance(pred, Tensor) else pred
    if _is_tracer(v):
        return None
    return bool(v)


def cond(pred, true_fn, false_fn, operands=(), name=None):
    """Conditional execution (reference ``conditional_block_op``).

    Eager (concrete pred): python-branches like the reference dygraph cond —
    only the taken branch runs, with full autograd through anything it
    touches.  Traced/static pred: lowers to ``lax.cond``; gradients then
    flow through ``operands`` (pass tensors explicitly — traced closures are
    captured as constants)."""
    operands = list(operands)
    taken = _concrete_bool(pred)
    if taken is not None:
        fn = true_fn if taken else false_fn
        return fn(*operands)

    hidden = [
        t for t in _closure_tensors(true_fn, false_fn)
        if t is not pred and all(t is not o for o in operands)
    ]
    n_ops = len(operands)

    def fwd(pred_v, *vals):
        op_vals, hid_vals = vals[:n_ops], vals[n_ops:]
        p = pred_v.reshape(()) if hasattr(pred_v, "reshape") else pred_v
        with _no_record(), _install(hidden, hid_vals):
            return lax.cond(
                p, _wrap_branch(true_fn), _wrap_branch(false_fn), list(op_vals)
            )

    return apply_op("cond", fwd, tuple([pred] + operands + hidden), {})


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """``lax.while_loop`` over Tensor loop_vars (reference ``while_op``;
    C++ ``operators/controlflow/while_op.cc``).

    Note: like the reference's RNN/while grad story, gradients through a
    while_loop require the body to be jax-differentiable; prefer
    ``lax.scan``-style fixed-trip loops (``paddle_tpu.ops.scan``) for
    training loops."""
    loop_vars = list(loop_vars)

    # eager concrete loop vars: python-loop with full autograd (reference
    # dygraph while semantics)
    first = cond_fn(*loop_vars)
    taken = _concrete_bool(first) if isinstance(first, Tensor) else None
    if taken is not None:
        state = list(loop_vars)
        keep = taken
        while keep:
            out = body_fn(*state)
            state = list(out) if isinstance(out, (tuple, list)) else [out]
            keep = bool(np_value(cond_fn(*state)))
        return tuple(state) if len(state) > 1 else state[0]

    hidden = [
        t for t in _closure_tensors(cond_fn, body_fn)
        if all(t is not v for v in loop_vars)
    ]
    n_loop = len(loop_vars)

    def fwd(*vals):
        lv, hid_vals = vals[:n_loop], vals[n_loop:]

        def c(state):
            out = cond_fn(*[Tensor(s) for s in state])
            return out._value.reshape(()) if isinstance(out, Tensor) else out

        def b(state):
            out = body_fn(*[Tensor(s) for s in state])
            out = out if isinstance(out, (tuple, list)) else [out]
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)

        with _no_record(), _install(hidden, hid_vals):
            return lax.while_loop(c, b, tuple(lv))

    return apply_op("while_loop", fwd, tuple(loop_vars + hidden), {})


def switch_case(branch_index, branch_fns, default=None, name=None):
    """``lax.switch`` (reference static.nn.switch_case)."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns.keys())
        fns = [branch_fns[k] for k in keys]
    else:
        fns = list(branch_fns)
    if default is not None:
        fns.append(default)

    hidden = _closure_tensors(*fns)

    def fwd(idx, *hid_vals):
        i = idx.reshape(()) if hasattr(idx, "reshape") else idx
        import jax.numpy as jnp

        i = jnp.clip(i, 0, len(fns) - 1)
        with _no_record(), _install(hidden, hid_vals):
            return lax.switch(i, [_wrap_branch(f) for f in fns], ())

    return apply_op("switch_case", fwd, tuple([branch_index] + hidden), {})
