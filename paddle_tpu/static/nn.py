"""static.nn — graph-building layer functions + structured control flow.

Reference: ``python/paddle/static/nn/`` (fc, control_flow cond/while_loop —
C++ twins ``operators/controlflow/conditional_block_op`` and ``while_op``).
cond/while_loop lower directly to ``lax.cond`` / ``lax.while_loop`` so they
work BOTH eagerly (dygraph Tensors, inside to_static traces) and while
recording a static Program.
"""
from __future__ import annotations

import contextlib
import types

import jax
from jax import lax

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["fc", "cond", "while_loop", "switch_case"]


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Reference static.nn.fc: flatten trailing dims then affine."""
    from ..nn.layer.common import Linear
    import paddle_tpu.nn.functional as F
    import numpy as np

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        x = x.reshape(list(x.shape[:num_flatten_dims]) + [in_dim])
    y = layer(x)
    if activation:
        y = getattr(F, activation)(y)
    return y


def _wrap_branch(fn):
    """Adapt a user branch fn over Tensors to raw arrays for lax."""

    def run(operands):
        t_ops = [Tensor(o) for o in operands]
        out = fn(*t_ops) if t_ops else fn()
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)
        return out._value if isinstance(out, Tensor) else out

    return run


def np_value(x):
    return x._value if isinstance(x, Tensor) else x


def _closure_tensors(*fns):
    """Tensors (incl. static Variables and Layer parameters) captured in the
    branch functions' closures — the reference discovers conditional-block
    inputs the same way, by scanning the sub-block's referenced vars."""
    from ..nn.layer.layers import Layer

    seen, out = set(), []

    def add(v, depth=0):
        if depth > 4 or id(v) in seen:
            return
        if isinstance(v, Tensor):
            seen.add(id(v))
            out.append(v)
        elif isinstance(v, Layer):
            seen.add(id(v))
            for q in v.parameters():
                add(q, depth)
        elif isinstance(v, (list, tuple, set)):
            seen.add(id(v))
            for q in v:
                add(q, depth + 1)
        elif isinstance(v, dict):
            seen.add(id(v))
            for q in v.values():
                add(q, depth + 1)
        elif getattr(v, "__self__", None) is not None:
            # bound method: scan the receiver (a Layer holding params, say)
            add(v.__self__, depth + 1)
        elif isinstance(v, types.FunctionType):
            # nested closure (e.g. dy2static branch wrappers close over the
            # user's branch fn, which closes over the tensors)
            seen.add(id(v))
            for cell in (v.__closure__ or ()):
                try:
                    add(cell.cell_contents, depth + 1)
                except ValueError:
                    pass
            for d in (v.__defaults__ or ()):
                add(d, depth + 1)

    for fn in fns:
        for cell in (getattr(fn, "__closure__", None) or ()):
            try:
                add(cell.cell_contents)
            except ValueError:
                pass
        for d in (getattr(fn, "__defaults__", None) or ()):
            add(d)
    return out


@contextlib.contextmanager
def _install(tensors, values):
    old = [t._value for t in tensors]
    for t, v in zip(tensors, values):
        t._value = v
    try:
        yield
    finally:
        for t, o in zip(tensors, old):
            t._value = o


@contextlib.contextmanager
def _no_record():
    """Suspend static recording while a control-flow body is traced (its
    inner ops execute on tracers inside the lowered lax region)."""
    from ..ops import dispatch

    prev = dispatch.STATIC_RECORDER
    dispatch.STATIC_RECORDER = None
    try:
        yield
    finally:
        dispatch.STATIC_RECORDER = prev


def _concrete_bool(pred):
    """Python truth value of pred when it is NOT symbolic/traced, else None."""
    from ..static.program import Variable
    from ..framework.tensor import _is_tracer

    if isinstance(pred, Variable):
        return None
    v = pred._value if isinstance(pred, Tensor) else pred
    if _is_tracer(v):
        return None
    return bool(v)


def cond(pred, true_fn, false_fn, operands=(), name=None):
    """Conditional execution (reference ``conditional_block_op``).

    Eager (concrete pred): python-branches like the reference dygraph cond —
    only the taken branch runs, with full autograd through anything it
    touches.  Traced/static pred: lowers to ``lax.cond``; gradients then
    flow through ``operands`` (pass tensors explicitly — traced closures are
    captured as constants)."""
    operands = list(operands)
    taken = _concrete_bool(pred)
    if taken is not None:
        fn = true_fn if taken else false_fn
        return fn(*operands)

    hidden = [
        t for t in _closure_tensors(true_fn, false_fn)
        if t is not pred and all(t is not o for o in operands)
    ]
    n_ops = len(operands)

    def fwd(pred_v, *vals):
        op_vals, hid_vals = vals[:n_ops], vals[n_ops:]
        p = pred_v.reshape(()) if hasattr(pred_v, "reshape") else pred_v
        with _no_record(), _install(hidden, hid_vals):
            return lax.cond(
                p, _wrap_branch(true_fn), _wrap_branch(false_fn), list(op_vals)
            )

    return apply_op("cond", fwd, tuple([pred] + operands + hidden), {})


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """``lax.while_loop`` over Tensor loop_vars (reference ``while_op``;
    C++ ``operators/controlflow/while_op.cc``).

    Note: like the reference's RNN/while grad story, gradients through a
    while_loop require the body to be jax-differentiable; prefer
    ``lax.scan``-style fixed-trip loops (``paddle_tpu.ops.scan``) for
    training loops."""
    loop_vars = list(loop_vars)

    # eager concrete loop vars: python-loop with full autograd (reference
    # dygraph while semantics)
    first = cond_fn(*loop_vars)
    taken = _concrete_bool(first) if isinstance(first, Tensor) else None
    if taken is not None:
        state = list(loop_vars)
        keep = taken
        while keep:
            out = body_fn(*state)
            state = list(out) if isinstance(out, (tuple, list)) else [out]
            keep = bool(np_value(cond_fn(*state)))
        return tuple(state) if len(state) > 1 else state[0]

    hidden = [
        t for t in _closure_tensors(cond_fn, body_fn)
        if all(t is not v for v in loop_vars)
    ]
    n_loop = len(loop_vars)

    def fwd(*vals):
        lv, hid_vals = vals[:n_loop], vals[n_loop:]

        def c(state):
            out = cond_fn(*[Tensor(s) for s in state])
            return out._value.reshape(()) if isinstance(out, Tensor) else out

        def b(state):
            out = body_fn(*[Tensor(s) for s in state])
            out = out if isinstance(out, (tuple, list)) else [out]
            return tuple(o._value if isinstance(o, Tensor) else o for o in out)

        with _no_record(), _install(hidden, hid_vals):
            return lax.while_loop(c, b, tuple(lv))

    return apply_op("while_loop", fwd, tuple(loop_vars + hidden), {})


def switch_case(branch_index, branch_fns, default=None, name=None):
    """``lax.switch`` (reference static.nn.switch_case)."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns.keys())
        fns = [branch_fns[k] for k in keys]
    else:
        fns = list(branch_fns)
    if default is not None:
        fns.append(default)

    hidden = _closure_tensors(*fns)

    def fwd(idx, *hid_vals):
        i = idx.reshape(()) if hasattr(idx, "reshape") else idx
        import jax.numpy as jnp

        i = jnp.clip(i, 0, len(fns) - 1)
        with _no_record(), _install(hidden, hid_vals):
            return lax.switch(i, [_wrap_branch(f) for f in fns], ())

    return apply_op("switch_case", fwd, tuple([branch_index] + hidden), {})


# ---------------------------------------------------------------------------
# Legacy fluid-style layer functions (reference ``python/paddle/static/nn``).
# Each builds the matching nn.Layer (parameters created eagerly, exactly the
# LayerHelper role) and applies it — in static mode the CALL records into the
# Program while the params live in the startup scope, mirroring the
# reference split. Sequence ops follow the TPU build's dense+lengths
# contract (LoD is a fluid-era CPU construct; dense padded tensors + masks
# are the XLA-native representation).
# ---------------------------------------------------------------------------

def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    from ..nn import Conv2D

    layer = Conv2D(int(input.shape[1 if data_format == "NCHW" else -1]),
                   num_filters, filter_size, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    return _act(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    from ..nn import Conv2DTranspose

    layer = Conv2DTranspose(
        int(input.shape[1 if data_format == "NCHW" else -1]), num_filters,
        filter_size, stride=stride, padding=padding, dilation=dilation,
        groups=groups, weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    return _act(layer(input), act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    from ..nn import Conv3D

    layer = Conv3D(int(input.shape[1 if data_format == "NCDHW" else -1]),
                   num_filters, filter_size, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", name=None):
    from ..nn import Conv3DTranspose

    layer = Conv3DTranspose(
        int(input.shape[1 if data_format == "NCDHW" else -1]), num_filters,
        filter_size, stride=stride, padding=padding, dilation=dilation,
        groups=groups, weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format)
    return _act(layer(input), act)


def _act(out, act):
    if act is None:
        return out
    import paddle_tpu.nn.functional as F

    return getattr(F, act)(out)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from ..nn import BatchNorm2D, BatchNorm1D

    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    cls = BatchNorm2D if len(input.shape) == 4 else BatchNorm1D
    layer = cls(c, momentum=momentum, epsilon=epsilon,
                weight_attr=param_attr, bias_attr=bias_attr,
                data_format=data_layout if len(input.shape) == 4 else "NCL")
    if is_test or use_global_stats:
        layer.eval()
    return _act(layer(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import LayerNorm

    shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    return _act(layer(input), act)


def group_norm(input, groups, epsilon=1e-05, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from ..nn import GroupNorm

    c = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = GroupNorm(groups, c, epsilon=epsilon,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format=data_layout)
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import InstanceNorm2D

    layer = InstanceNorm2D(int(input.shape[1]), epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              enable_scale_and_shift=False, name=None, moving_mean_name=None,
              moving_variance_name=None, do_model_average_for_mean_and_var=True,
              slot_dim=-1, summary_decay_rate=0.9999999, sync_stats=False):
    """reference data_norm (CTR models): normalization by batch summaries
    — statistics are detached (the reference treats the summaries as
    non-differentiable accumulators) — with optional learned scale/shift
    parameters when ``enable_scale_and_shift``."""
    from ..nn.layer.layers import Layer
    from ..ops.dispatch import apply_op

    d = int(input.shape[-1])
    scale = shift = None
    if enable_scale_and_shift:
        helper = Layer()
        scale = helper.create_parameter([d], attr=param_attr)
        shift = helper.create_parameter([d], attr=param_attr, is_bias=True)

    def fwd(x, sc=None, sh=None):
        import jax
        import jax.numpy as jnp

        mean = jax.lax.stop_gradient(jnp.mean(x, axis=0, keepdims=True))
        var = jax.lax.stop_gradient(jnp.var(x, axis=0, keepdims=True))
        y = (x - mean) / jnp.sqrt(var + epsilon)
        if sc is not None:
            y = y * sc + sh
        return y

    args = (input,) if scale is None else (input, scale, shift)
    out = apply_op("data_norm", fwd, args, {})
    return _act(out, act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from ..nn import Embedding

    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """reference sparse_embedding (PS lookup table): on the TPU build this
    is the SelectedRows-grad embedding (sparse=True)."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ..nn import PReLU

    num = 1
    if mode == "channel":
        num = int(x.shape[1 if data_format == "NCHW" else -1])
    elif mode == "element":
        import numpy as _np

        num = int(_np.prod(x.shape[1:]))
    layer = PReLU(num_parameters=num, weight_attr=param_attr,
                  data_format=data_format)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference static spectral_norm: returns the spectrally normalized
    weight (power iteration, like nn.utils.spectral_norm's estimate)."""
    from ..ops.dispatch import apply_op

    def fwd(w):
        import jax.numpy as jnp

        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), w.dtype)
        v = None
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / sigma

    return apply_op("spectral_norm", fwd, (weight,), {})


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    from ..nn import Bilinear

    layer = Bilinear(int(x.shape[-1]), int(y.shape[-1]), size,
                     weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(x, y), act)


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import DeformConv2D

    layer = DeformConv2D(int(x.shape[1]), num_filters, filter_size,
                         stride=stride, padding=padding, dilation=dilation,
                         groups=groups, deformable_groups=deformable_groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
    return layer(x, offset, mask)


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=5, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static/nn/common.py
    nce): per sample, the true class plus ``num_neg_samples`` uniform
    negatives scored by a class-embedding matrix; returns per-sample NCE
    loss [N, 1]."""
    import numpy as _np

    from ..framework import random as rnd
    from ..framework.tensor import Tensor as _T
    from ..nn.layer.layers import Layer
    from ..ops.dispatch import apply_op

    helper = Layer()
    dim = int(input.shape[-1])
    w = helper.create_parameter([num_total_classes, dim], attr=param_attr)
    b = helper.create_parameter([num_total_classes], attr=bias_attr,
                                is_bias=True)
    key = rnd.next_key()

    def fwd(x, y, wv, bv):
        import jax
        import jax.numpy as jnp

        n = x.shape[0]
        neg = jax.random.randint(key, (n, num_neg_samples), 0,
                                 num_total_classes)
        y2 = y.reshape(-1, 1)
        cls = jnp.concatenate([y2, neg], axis=1)          # [N, 1+K]
        logits = jnp.einsum("nd,nkd->nk", x, wv[cls]) + bv[cls]
        labels = jnp.concatenate(
            [jnp.ones((n, 1)), jnp.zeros((n, num_neg_samples))], axis=1)
        per = (jax.nn.softplus(logits) - labels * logits).mean(axis=1)
        return per.reshape(-1, 1)

    return apply_op("nce", fwd, (input, label, w, b), {})


def case(pred_fn_pairs, default=None, name=None):
    """reference static/nn/control_flow.py case: first true predicate
    wins."""

    def build(pairs):
        pred, fn = pairs[0]
        rest = pairs[1:]
        if not rest:
            if default is None:
                return fn()
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: build(rest))

    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    return build(list(pred_fn_pairs))


class StaticRNN:
    """reference StaticRNN: build a per-timestep recurrence over [T, B, ...]
    inputs. The TPU build executes the user-described step eagerly per
    timestep (recording in static mode), which is exactly the reference's
    unrolled-program semantics."""

    def __init__(self, name=None):
        self._inputs = []       # (tensor [T, B, ...])
        self._memories = []     # dicts: init, var (current), next
        self._outputs = []
        self._built = False

    def step(self):
        import contextlib

        return contextlib.nullcontext(self)

    def step_input(self, x):
        self._inputs.append(x)
        self._in_slots = getattr(self, "_in_slots", [])
        slot = {"seq": x, "cur": None}
        self._in_slots.append(slot)
        return _SlotRef(slot)

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        from .. import ops

        if init is None:
            if batch_ref is None:
                raise ValueError("memory needs init or batch_ref")
            b = batch_ref.shape[ref_batch_dim_idx]
            init = ops.full([b] + list(shape)[1:] if shape else [b],
                            init_value, "float32")
        slot = {"cur": init, "next": None, "init": init}
        self._memories.append(slot)
        return _SlotRef(slot)

    def update_memory(self, mem_ref, new_val):
        mem_ref._slot["next"] = new_val

    def step_output(self, out):
        self._out_ref = getattr(self, "_out_ref", [])
        self._outputs.append(out)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def __call__(self):
        raise RuntimeError(
            "StaticRNN on the TPU build is used through with rnn.step(): "
            "build the step ONCE against SlotRefs, then call rnn.run()")

    def run(self, step_fn, seq_len=None):
        """Execute ``step_fn(t)`` per timestep; the user's closures read
        SlotRefs. Returns stacked step outputs."""
        from .. import ops

        t_max = seq_len or int(self._in_slots[0]["seq"].shape[0])
        outs = []
        for t in range(t_max):
            for slot in self._in_slots:
                slot["cur"] = slot["seq"][t]
            self._outputs = []
            step_fn(t)
            outs.append(self._outputs)
            for m in self._memories:
                if m["next"] is not None:
                    m["cur"], m["next"] = m["next"], None
        stacked = [ops.stack([o[i] for o in outs], axis=0)
                   for i in range(len(outs[0]))]
        return stacked[0] if len(stacked) == 1 else stacked


class _SlotRef:
    def __init__(self, slot):
        self._slot = slot

    def value(self):
        return self._slot["cur"]

    def __getattr__(self, name):
        return getattr(self._slot["cur"], name)


def crf_decoding(input, param_attr=None, length=None, label=None,
                 transition=None, include_bos_eos_tag=True, name=None):
    """reference crf_decoding: viterbi over CRF emissions. ``transition``
    may be passed directly (the modern square [n_tags, n_tags] form, where
    the last two tags are BOS/EOS when ``include_bos_eos_tag``) or owned
    via param_attr; the emission width must equal the tag count."""
    from .. import ops
    from ..nn.functional.sequence import viterbi_decode
    from ..nn.layer.layers import Layer

    n = int(input.shape[-1])
    if transition is None:
        helper = Layer()
        transition = helper.create_parameter([n, n], attr=param_attr)
    if length is None:
        length = ops.full([input.shape[0]], input.shape[1], "int64")
    _, path = viterbi_decode(input, transition, length,
                             include_bos_eos_tag=include_bos_eos_tag)
    return path


# -- dense+lengths sequence ops ---------------------------------------------

def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Dense contract: x [B, T, ...] with ``length`` [B] — returns (padded,
    length). (The reference consumes LoD; here padding is explicit.)"""
    from .. import ops

    if length is None:
        raise ValueError("dense sequence_pad needs explicit length")
    from ..nn.functional.sequence import sequence_mask

    m = sequence_mask(length, maxlen=x.shape[1], dtype="bool")
    while len(m.shape) < len(x.shape):
        m = m.unsqueeze(-1)
    out = ops.where(m, x, ops.full_like(x, float(pad_value)))
    return out, length


def sequence_unpad(x, length, name=None):
    """Returns the dense tensor with positions past ``length`` zeroed (the
    dense stand-in for LoD compaction)."""
    from .. import ops
    from ..nn.functional.sequence import sequence_mask

    m = sequence_mask(length, maxlen=x.shape[1], dtype="bool")
    while len(m.shape) < len(x.shape):
        m = m.unsqueeze(-1)
    return ops.where(m, x, ops.zeros_like(x))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None, name=None):
    from .. import ops
    from ..nn.functional.sequence import sequence_mask

    x = input
    if length is not None:
        m = sequence_mask(length, maxlen=x.shape[1], dtype="float32")
        while len(m.shape) < len(x.shape):
            m = m.unsqueeze(-1)
    else:
        m = ops.ones_like(x)
    pt = pool_type.lower()
    if pt == "sum":
        return (x * m).sum(axis=1)
    if pt in ("average", "mean"):
        return (x * m).sum(axis=1) / m.sum(axis=1).clip(min=1.0)
    if pt == "sqrt":
        return (x * m).sum(axis=1) / m.sum(axis=1).clip(min=1.0).sqrt()
    if pt == "max":
        neg = ops.full_like(x, -1e30)
        return ops.where(m.astype("bool"), x, neg).max(axis=1)
    if pt == "first":
        return x[:, 0]
    if pt == "last":
        return sequence_last_step(x, length)
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input, length=None):
    return input[:, 0]


def sequence_last_step(input, length=None):
    from .. import ops

    if length is None:
        return input[:, -1]
    idx = (length - 1).astype("int64")
    return ops.stack([input[i, int(idx_i)] for i, idx_i in
                      enumerate(idx.numpy().tolist())], axis=0) \
        if not _is_traced(input) else _gather_time(input, idx)


def _is_traced(x):
    import jax

    return isinstance(x._value, jax.core.Tracer)


def _gather_time(x, idx):
    from ..ops.dispatch import apply_op

    def fwd(xv, iv):
        import jax.numpy as jnp

        sel = jnp.take_along_axis(
            xv, iv.reshape((-1, 1) + (1,) * (xv.ndim - 2)).astype(
                jnp.int32), axis=1)
        return jnp.squeeze(sel, axis=1)

    return apply_op("sequence_last_step", fwd, (x, idx), {})


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    from .. import ops
    from ..nn.functional.sequence import sequence_mask

    x = input
    if length is not None:
        m = sequence_mask(length, maxlen=x.shape[1], dtype="bool")
        while len(m.shape) < len(x.shape):
            m = m.unsqueeze(-1)
        x = ops.where(m, x, ops.full_like(x, -1e30))
    import paddle_tpu.nn.functional as F

    return F.softmax(x, axis=1)


def sequence_reverse(x, length=None, name=None):
    """Reverse each sequence's VALID prefix (dense+lengths)."""
    from ..ops.dispatch import apply_op

    def fwd(xv, lv=None):
        import jax.numpy as jnp

        t = xv.shape[1]
        if lv is None:
            return xv[:, ::-1]
        pos = jnp.arange(t)[None, :]
        src = jnp.where(pos < lv[:, None], lv[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)).astype(
                jnp.int32), axis=1)

    args = (x,) if length is None else (x, length)
    return apply_op("sequence_reverse", fwd, args, {})


def sequence_concat(input, name=None):
    """Dense contract: concatenate along time."""
    from .. import ops

    return ops.concat(input, axis=1)


def sequence_expand(x, y, ref_level=-1, name=None):
    """Dense stand-in: tile x rows to match y's time dim."""
    from .. import ops

    reps = int(y.shape[1]) if len(y.shape) > 1 else 1
    return ops.repeat_interleave(x, reps, axis=0)


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_reshape(input, new_dim):
    from .. import ops

    b = input.shape[0]
    return ops.reshape(input, [b, -1, new_dim])


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows over time (reference sequence_enumerate)."""
    from ..ops.dispatch import apply_op

    def fwd(xv):
        import jax.numpy as jnp

        t = xv.shape[1]
        outs = []
        for w in range(win_size):
            shifted = jnp.concatenate(
                [xv[:, w:], jnp.full_like(xv[:, :w], pad_value)], axis=1)
            outs.append(shifted)
        return jnp.stack(outs, axis=-1)

    return apply_op("sequence_enumerate", fwd, (input,), {})


def sequence_pool_first(x):
    return x[:, 0]


def sequence_slice(input, offset, length, name=None):
    from ..ops.dispatch import apply_op

    def fwd(xv, off, ln):
        import jax.numpy as jnp

        t = xv.shape[1]
        pos = jnp.arange(t)[None, :]
        idx = (off.reshape(-1, 1) + pos) % t
        keep = pos < ln.reshape(-1, 1)
        sel = jnp.take_along_axis(
            xv, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)).astype(
                jnp.int32), axis=1)
        mask = keep.reshape(keep.shape + (1,) * (xv.ndim - 2))
        return jnp.where(mask, sel, 0)

    return apply_op("sequence_slice", fwd, (input, offset, length), {})


def sequence_scatter(input, index, updates, name=None):
    from ..ops.dispatch import apply_op

    def fwd(xv, iv, uv):
        import jax.numpy as jnp

        b = jnp.arange(xv.shape[0]).reshape(-1, 1)
        b = jnp.broadcast_to(b, iv.shape)
        return xv.at[b, iv].add(uv)

    return apply_op("sequence_scatter", fwd, (input, index, updates), {})


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window conv over time (reference sequence_conv): dense form
    is a Conv1D with same-padding over [B, T, C]."""
    from ..nn import Conv1D

    layer = Conv1D(int(input.shape[-1]), num_filters, filter_size,
                   padding="SAME" if padding else 0, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format="NLC")
    return _act(layer(input), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference row_conv / DeepSpeech2): each
    timestep mixes the next ``future_context_size`` frames with learned
    per-channel weights."""
    from ..nn.layer.layers import Layer
    from ..ops.dispatch import apply_op

    helper = Layer()
    d = int(input.shape[-1])
    w = helper.create_parameter([future_context_size + 1, d],
                                attr=param_attr)

    def fwd(xv, wv):
        import jax.numpy as jnp

        t = xv.shape[1]
        out = jnp.zeros_like(xv)
        for k in range(future_context_size + 1):
            shifted = jnp.concatenate(
                [xv[:, k:], jnp.zeros_like(xv[:, :k])], axis=1)
            out = out + shifted * wv[k][None, None, :]
        return out

    return _act(apply_op("row_conv", fwd, (input, w), {}), act)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-box head (reference static/nn/multi_box_head): per feature
    map, a conv predicts box offsets and class scores over generated prior
    boxes; returns (mbox_locs, mbox_confs, prior_boxes, variances)."""
    import numpy as _np

    from .. import ops
    from ..nn import Conv2D

    if min_sizes is None:
        n = len(inputs)
        step = int((max_ratio - min_ratio) / max(n - 2, 1))
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n - 1]

    def _cell_sizes(i, ar):
        """The per-cell (w, h) prior list — single source of truth for BOTH
        the conv channel count and the generated boxes."""
        sizes = [(min_sizes[i], min_sizes[i])]
        if max_sizes:
            s_ = _np.sqrt(min_sizes[i] * max_sizes[i])
            sizes.append((s_, s_))
        for a in ar:
            if a == 1:
                continue
            w_ = min_sizes[i] * _np.sqrt(a)
            h_ = min_sizes[i] / _np.sqrt(a)
            sizes.append((w_, h_))
            if flip:
                sizes.append((h_, w_))
        return sizes

    locs, confs, priors_all, vars_all = [], [], [], []
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i]
        n_priors = len(_cell_sizes(i, ar))
        c_in = int(feat.shape[1])
        loc_conv = Conv2D(c_in, n_priors * 4, kernel_size, padding=pad,
                          stride=stride)
        conf_conv = Conv2D(c_in, n_priors * num_classes, kernel_size,
                           padding=pad, stride=stride)
        loc = loc_conv(feat)
        conf = conf_conv(feat)
        b = int(feat.shape[0])
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([b, -1, 4]))
        confs.append(conf.transpose([0, 2, 3, 1]).reshape(
            [b, -1, num_classes]))
        # prior boxes on the host (static data, like the reference op)
        fh, fw = int(feat.shape[2]), int(feat.shape[3])
        sw = steps[i] if steps else img_w / fw
        sh = steps[i] if steps else img_h / fh
        boxes = []
        for y in range(fh):
            for x in range(fw):
                cx, cy = (x + offset) * sw, (y + offset) * sh
                for (bw, bh) in _cell_sizes(i, ar):
                    box = [(cx - bw / 2) / img_w, (cy - bh / 2) / img_h,
                           (cx + bw / 2) / img_w, (cy + bh / 2) / img_h]
                    if clip:
                        box = [min(max(v, 0.0), 1.0) for v in box]
                    boxes.append(box)
        pb = _np.asarray(boxes, _np.float32)
        priors_all.append(ops.to_tensor(pb))
        vars_all.append(ops.to_tensor(
            _np.tile(_np.asarray(variance, _np.float32), (len(boxes), 1))))
    mbox_locs = ops.concat(locs, axis=1)
    mbox_confs = ops.concat(confs, axis=1)
    boxes = ops.concat(priors_all, axis=0)
    variances = ops.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


from ..static.compat import py_func  # noqa: E402,F401

__all__ += [
    "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "data_norm",
    "embedding", "sparse_embedding", "prelu", "spectral_norm",
    "bilinear_tensor_product", "deform_conv2d", "nce", "case", "StaticRNN",
    "crf_decoding", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_softmax",
    "sequence_reverse", "sequence_concat", "sequence_expand",
    "sequence_expand_as", "sequence_reshape", "sequence_enumerate",
    "sequence_slice", "sequence_scatter", "sequence_conv", "row_conv",
    "multi_box_head", "py_func",
]
