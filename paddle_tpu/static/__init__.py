"""paddle.static — the declarative (graph) programming surface.

Reference: ``python/paddle/fluid/framework.py`` (Program/Variable/default
programs), ``fluid/executor.py:621 Executor`` (``run:1104``),
``fluid/backward.py append_backward``, ``fluid/compiler.py CompiledProgram``.

TPU-native redesign: a Program is an **op tape**, not a protobuf graph.
While a ``program_guard`` is active, every framework op that touches a
symbolic ``Variable`` records a node (forward callable + arg refs + static
attrs) instead of executing; shapes/dtypes come from ``jax.eval_shape``.
``Executor.run`` replays the tape once inside ``jax.jit`` — parameters and
optimizer state thread through exactly like the dygraph CompiledStep, and
``append_backward`` / ``Optimizer.minimize`` lower to ``jax.grad`` over the
replayed loss.  The "executor" is therefore a cached XLA executable per
(program, feed/fetch signature) — InterpreterCore's instruction list is the
compiled HLO schedule itself.
"""
from .program import (
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    program_guard,
    in_static_build,
)
from .executor import Executor, CompiledProgram, global_scope
from ..jit.save_load import InputSpec  # noqa: F401  (reference static/input.py)
from .backward import append_backward
from .io import save_inference_model, load_inference_model
from . import nn
from .compat import *  # noqa: F401,F403
from . import compat  # noqa: F401

__all__ = [
    "Program", "Variable", "data", "default_main_program",
    "default_startup_program", "program_guard", "Executor",
    "CompiledProgram", "append_backward", "save_inference_model",
    "load_inference_model", "nn", "global_scope", "in_static_build",
]
from . import quantization  # noqa: F401  (reference static/quantization/)
from .sharding import shard_static_optimizer  # noqa: F401

__all__ += ["quantization", "shard_static_optimizer"]
