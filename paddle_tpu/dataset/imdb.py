"""paddle.dataset.imdb (reference ``dataset/imdb.py``)."""
from ..text import Imdb


def _reader(mode):
    def reader():
        ds = Imdb(mode=mode)
        for i in range(len(ds)):
            doc, label = ds[i]
            yield list(doc), int(label)

    return reader


def train(word_idx=None):
    return _reader("train")


def test(word_idx=None):
    return _reader("test")


def word_dict():
    return {i: i for i in range(5000)}
