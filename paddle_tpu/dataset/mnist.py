"""paddle.dataset.mnist (reference ``dataset/mnist.py``): sample readers
yielding (image[784] float32 in [-1,1], label int)."""
from ..vision.datasets import MNIST


def _reader(mode):
    def reader():
        ds = MNIST(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1) * 2.0 - 1.0, int(label)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
