"""paddle.dataset (reference ``python/paddle/dataset/``: legacy reader-style
dataset loaders — mnist.train() returns a sample generator).

Offline policy: each loader yields from the framework's synthetic dataset
surrogates (vision/datasets, text), keeping the generator item structure of
the reference loaders.
"""
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import imdb  # noqa: F401
from . import uci_housing  # noqa: F401
