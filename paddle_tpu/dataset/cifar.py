"""paddle.dataset.cifar (reference ``dataset/cifar.py``)."""
from ..vision.datasets import Cifar10


def _reader(mode):
    def reader():
        ds = Cifar10(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            yield img.reshape(-1), int(label)

    return reader


def train10():
    return _reader("train")


def test10():
    return _reader("test")
