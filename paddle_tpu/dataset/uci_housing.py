"""paddle.dataset.uci_housing (reference ``dataset/uci_housing.py``)."""
from ..text import UCIHousing


def _reader(mode):
    def reader():
        ds = UCIHousing(mode=mode)
        for i in range(len(ds)):
            x, y = ds[i]
            yield x, y

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
