"""paddle_tpu — a TPU-native deep learning framework with the PaddlePaddle
API surface, built from scratch on jax/XLA/Pallas/pjit.

Architecture (vs the reference at /root/reference — see SURVEY.md):
 - eager "dygraph" execution = per-op XLA dispatch with a jax.vjp-backed
   autograd tape (paddle_tpu.autograd.engine);
 - static/jit path = whole-train-step functionalization compiled to one XLA
   program (paddle_tpu.jit), replacing ProgramDesc+Executor;
 - distributed = jax.sharding.Mesh + shard_map collectives over ICI/DCN,
   replacing NCCL rings / ProcessGroup (paddle_tpu.distributed);
 - hot kernels = Pallas (paddle_tpu.ops.pallas).
"""
from __future__ import annotations

__version__ = "0.1.0"

# normalize the jax surface BEFORE any submodule does `from jax import
# shard_map` (older runtimes keep it under jax.experimental)
from .framework.jax_compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

# framework core
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
    get_default_dtype,
    set_default_dtype,
)
import jax.numpy as _jnp_for_dtype

# paddle.dtype / paddle.bool (reference: core.VarDesc.VarType aliases; here
# dtypes ARE numpy/jnp dtypes, so the constructor-alias is jnp.dtype)
dtype = _jnp_for_dtype.dtype
from .framework.dtype import bool_ as bool  # noqa: F401,A001

from .framework.place import (  # noqa: F401
    NPUPlace,
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    get_device,
    set_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .framework.random import (  # noqa: F401
    seed, get_rng_state, set_rng_state, get_cuda_rng_state,
    set_cuda_rng_state,
)
from .framework.flags import set_flags, get_flags  # noqa: F401
from .framework.tensor import Parameter, Tensor, to_tensor, is_tensor  # noqa: F401

# the whole tensor-op surface (also patches Tensor methods)
from .distributed.data_parallel import DataParallel  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import add_n, einsum  # noqa: F401
from .ops.random import (  # noqa: F401
    bernoulli,
    multinomial,
    normal,
    poisson,
    rand,
    randint,
    randint_like,
    randn,
    randperm,
    standard_normal,
    uniform,
)

from .autograd import no_grad, enable_grad, set_grad_enabled, grad  # noqa: F401
from . import autograd  # noqa: F401

# Subsystems are appended here as they land (build order in SURVEY.md §7).
from . import nn  # noqa: F401
from .nn.layer.container import LayerList, ParameterList, Sequential  # noqa: F401
from .nn.layer.layers import ParamAttr  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import static  # noqa: F401
from . import profiler  # noqa: F401
from . import analysis  # noqa: F401
from . import fault  # noqa: F401
from . import hapi  # noqa: F401
from . import distribution  # noqa: F401
from . import sparse  # noqa: F401
from . import text  # noqa: F401
from . import onnx  # noqa: F401
from . import incubate  # noqa: F401
from . import utils  # noqa: F401
from . import device  # noqa: F401
from . import cost_model  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import version  # noqa: F401
from . import sysconfig  # noqa: F401
from . import compat  # noqa: F401
from . import reader  # noqa: F401
from . import hub  # noqa: F401
from . import callbacks  # noqa: F401
from . import dataset  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import tensor  # noqa: F401
from .batch import batch  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi.model_summary import summary, flops  # noqa: F401
from .framework.io import load, save  # noqa: F401

_static_mode = False


def enable_static():
    """Switch to declarative mode: framework ops touching static.Variables
    record into the current Program (reference paddle.enable_static)."""
    global _static_mode
    _static_mode = True
    from .ops import dispatch
    from .static.program import _recorder

    dispatch.STATIC_RECORDER = _recorder


def disable_static(place=None):
    global _static_mode
    _static_mode = False
    from .ops import dispatch
    from .static import program as _prog

    if not _prog._guard_stack:
        dispatch.STATIC_RECORDER = None


def in_dynamic_mode():
    return not _static_mode


def set_grad_enabled_ctx(mode):
    return set_grad_enabled(mode)


def is_grad_enabled():
    from .autograd import is_grad_enabled as _ige

    return _ige()


def device_count():
    import jax

    return jax.local_device_count()


def set_printoptions(**kwargs):
    import numpy as np

    np.set_printoptions(**{k: v for k, v in kwargs.items() if k in ("precision", "threshold", "edgeitems", "linewidth")})


def __getattr__(name):
    # paddle.distributed is imported lazily: it builds mesh/topology state on
    # import, which not every single-chip program needs at startup
    if name == "distributed":
        import importlib

        return importlib.import_module(".distributed", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def check_shape(shape):
    """Reference ``fluid/data_feeder.py check_shape``: validate a shape spec
    (ints or a 1-D integer Tensor; -1 allowed as the dynamic marker)."""
    from .framework.tensor import Tensor as _T

    if isinstance(shape, _T):
        if shape.ndim != 1:
            raise TypeError("shape tensor must be 1-D")
        return
    for s_ in shape:
        if not isinstance(s_, (int,)) or (s_ < 0 and s_ != -1):
            raise TypeError(
                f"shape entries must be non-negative ints or -1, got {s_!r}")


def disable_signal_handler():
    """Reference ``fluid/framework.py:736``: Paddle installs fault-signal
    handlers at import; jax/XLA installs none, so there is nothing to
    disable — kept for call-site compatibility."""
    return None
