"""Regularizers (reference ``python/paddle/fluid/regularizer.py``;
applied by folding into grads before the optimizer update)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff


class L1Decay(WeightDecayRegularizer):
    pass


class L2Decay(WeightDecayRegularizer):
    pass
