"""Declarative SLOs with multi-window burn-rate alerting.

Aggregate telemetry still needs a human watching it; this module turns the
registry into pages. An SLO is a one-line spec string evaluated against the
live :class:`~paddle_tpu.profiler.telemetry.Telemetry` registry::

    serve.latency_s p95 < 0.5        # histogram percentile (reservoir)
    serve.ttft_s    p95 < 1.0
    serve.queue_depth    < 16        # gauge (or counter) by bare name
    fault.giveups       == 0         # absent counters read as 0
    serve.decode_steps rate > 1.0 @ 0.999   # counter rate/s, objective 0.999

Grammar: ``<metric> [<stat>] <op> <threshold> [@ <objective>]`` where
``stat`` is ``p<NN>`` / ``mean`` / ``count`` / ``sum`` / ``min`` / ``max``
/ ``rate`` (counter delta per second between checks) and ``op`` is one of
``< <= > >= == !=``.

:class:`SLOMonitor` samples every spec on each :meth:`~SLOMonitor.check`
(the Scheduler ticks it every ``slo_check_every`` steps; the
``TelemetryLogger`` callback every ``log_freq`` batches) and keeps a
timestamped compliance window per spec. Alerting follows the SRE
multi-window burn-rate recipe: with error budget ``1 - objective``, the
burn rate over a window is ``bad_fraction / budget``, and an alert fires
only when EVERY configured window exceeds its threshold — the short window
gives fast detection, the long one keeps one-sample blips from paging.
Alerts dedupe until the spec recovers (all windows back under threshold).

Sinks are pluggable callables; :func:`log_alert_sink` (RuntimeWarning) and
:class:`JsonlAlertSink` ship in the box. The clock is injectable so burn
windows are testable without sleeping.
"""
from __future__ import annotations

import json
import re
import time
import warnings
from collections import deque

__all__ = [
    "SLOSpec",
    "SLOMonitor",
    "log_alert_sink",
    "JsonlAlertSink",
    "DEFAULT_WINDOWS",
    "SERVING_SLOS",
]

#: Shipped serving-overload objectives (``Scheduler(slo=
#: serving.default_slo_monitor())`` wires them in). Counter rates and
#: histogram percentiles ONLY — never the ``serve.requests_in_flight`` /
#: ``serve.queue_depth`` gauges, which are RETIRED (absent, not 0) once a
#: scheduler drains; a gauge-based spec would fall through to the
#: counters-read-as-0 path and silently stop measuring. The rate specs
#: page when sheds/timeouts/OOM evictions burn faster than ~1/s across
#: the burn windows — i.e. sustained overload, not a single rejected
#: request.
SERVING_SLOS = (
    "serve.shed rate < 1 @ 0.999",
    "serve.timeouts rate < 1 @ 0.999",
    "serve.oom_evictions rate < 1 @ 0.999",
    "serve.errors rate < 1 @ 0.999",
    "serve.latency_s p95 < 2.0 @ 0.99",
    "serve.ttft_s p95 < 1.0 @ 0.99",
)

#: (window_seconds, burn-rate threshold): fast page at 14.4x (2% of a
#: 30-day budget in an hour, scaled down to serving-loop timescales) plus a
#: slower confirmation window. All windows must burn for an alert.
DEFAULT_WINDOWS = ((60.0, 14.4), (600.0, 6.0))

_OPS = {
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_STAT_RE = re.compile(r"^(p\d{1,2}(\.\d+)?|mean|count|sum|min|max|rate)$")
_SPEC_RE = re.compile(
    r"^\s*(?P<metric>[A-Za-z0-9_.\-/]+)"
    r"(\s+(?P<stat>p\d{1,2}(\.\d+)?|mean|count|sum|min|max|rate))?"
    r"\s*(?P<op><=|>=|==|!=|<|>)"
    r"\s*(?P<thr>[-+0-9.eE]+)"
    r"(\s*@\s*(?P<obj>0?\.\d+|1(\.0*)?))?\s*$")


class SLOSpec:
    """One parsed objective: ``value(telemetry)`` resolves the live value,
    ``evaluate`` applies the comparison."""

    def __init__(self, metric, op, threshold, stat=None, objective=None,
                 name=None):
        if op not in _OPS:
            raise ValueError(f"unknown comparison {op!r}")
        if stat is not None and not _STAT_RE.match(stat):
            raise ValueError(f"unknown stat {stat!r}")
        self.metric = metric
        self.stat = stat
        self.op = op
        self.threshold = float(threshold)
        self.objective = float(objective) if objective is not None else None
        if self.objective is not None and not (0.0 < self.objective <= 1.0):
            raise ValueError(f"objective {objective} outside (0, 1]")
        self.name = name or self._default_name()

    def _default_name(self):
        stat = f" {self.stat}" if self.stat else ""
        return f"{self.metric}{stat} {self.op} {self.threshold:g}"

    @classmethod
    def parse(cls, text):
        """Parse a spec string (see module grammar). Raises ``ValueError``
        with the offending text on mismatch."""
        m = _SPEC_RE.match(text)
        if m is None:
            raise ValueError(
                f"unparseable SLO spec {text!r} (want '<metric> [stat] "
                f"<op> <threshold> [@ <objective>]')")
        return cls(m.group("metric"), m.group("op"), float(m.group("thr")),
                   stat=m.group("stat"), objective=m.group("obj"),
                   name=text.strip())

    def value(self, telemetry, rate_state=None, now=None):
        """Resolve the spec's current value against the registry. Returns
        None when there is no data yet (histogram stat with no samples, or
        a ``rate`` on its first reading)."""
        if self.stat == "rate":
            cur = telemetry.counters().get(self.metric)
            if cur is None:
                cur = 0.0
            now = time.monotonic() if now is None else now
            prev = None if rate_state is None \
                else rate_state.get(self.metric)
            if rate_state is not None:
                rate_state[self.metric] = (now, float(cur))
            if prev is None or now <= prev[0]:
                return None
            return (float(cur) - prev[1]) / (now - prev[0])
        if self.stat is not None:
            st = telemetry.stat(self.metric, self.stat)
            return st  # None when the histogram has no samples
        gauges = telemetry.gauges()
        if self.metric in gauges:
            return gauges[self.metric]
        # counters (absent == never incremented == 0: `fault.giveups == 0`
        # must hold on a clean process)
        return float(telemetry.counters().get(self.metric, 0.0))

    def evaluate(self, telemetry, rate_state=None, now=None):
        """→ ``(ok, value)``; ``(None, None)`` when there is no data."""
        v = self.value(telemetry, rate_state=rate_state, now=now)
        if v is None:
            return None, None
        return bool(_OPS[self.op](float(v), self.threshold)), float(v)

    def __repr__(self):
        return f"<SLOSpec {self.name!r}>"


def log_alert_sink(alert):
    """Default sink: a ``RuntimeWarning`` naming the spec, value and burn
    rates (shows up in logs/pytest without any wiring)."""
    wins = ", ".join(f"{int(w['window_s'])}s burn {w['burn_rate']:.1f}x"
                     f" (max {w['max_burn']:g})"
                     for w in alert["windows"])
    warnings.warn(
        f"SLO burn: {alert['spec']} — value {alert['value']:g} "
        f"violates the objective; {wins}", RuntimeWarning, stacklevel=3)


class JsonlAlertSink:
    """Append alerts as JSON lines (one object per alert) to ``path``."""

    def __init__(self, path):
        self.path = str(path)

    def __call__(self, alert):
        with open(self.path, "a") as f:
            f.write(json.dumps(alert) + "\n")


class _SpecState:
    __slots__ = ("samples", "firing", "last_value", "last_ok", "alerts")

    def __init__(self, history):
        self.samples = deque(maxlen=history)  # (t, ok) compliance series
        self.firing = False
        self.last_value = None
        self.last_ok = None
        self.alerts = 0


class SLOMonitor:
    """Evaluate SLO specs against the telemetry registry and page through
    sinks on multi-window burn.

    Args:
        specs: iterable of :class:`SLOSpec` or spec strings.
        objective: default availability objective (fraction of checks that
            must pass) for specs that don't carry their own ``@``.
        windows: ``((seconds, max_burn), ...)`` — ALL windows must exceed
            their burn threshold to alert.
        sinks: callables invoked with the alert dict; defaults to
            :func:`log_alert_sink`.
        telemetry: registry to read; defaults to the process-wide one.
        clock: injectable time source (seconds; ``time.monotonic``).
        history: bounded per-spec compliance samples.
    """

    def __init__(self, specs, objective=0.99, windows=DEFAULT_WINDOWS,
                 sinks=None, telemetry=None, clock=time.monotonic,
                 history=4096):
        self.specs = [s if isinstance(s, SLOSpec) else SLOSpec.parse(s)
                      for s in specs]
        if not (0.0 < float(objective) < 1.0):
            raise ValueError("objective must be in (0, 1)")
        self.objective = float(objective)
        self.windows = tuple((float(w), float(b)) for w, b in windows)
        if not self.windows:
            raise ValueError("at least one burn window required")
        self.sinks = list(sinks) if sinks is not None else [log_alert_sink]
        self._telemetry = telemetry
        self.clock = clock
        self._state = {s.name: _SpecState(history) for s in self.specs}
        self._rate_state = {}
        self.alerts = []
        self.checks = 0

    def _tm(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import telemetry as _telemetry

        return _telemetry.get_telemetry()

    def _budget(self, spec):
        obj = spec.objective if spec.objective is not None else self.objective
        return max(1.0 - obj, 1e-9)

    def burn_rates(self, spec, now=None):
        """Per-window burn for one spec: ``[{window_s, burn_rate,
        max_burn, samples}]`` over whatever samples each window holds."""
        now = self.clock() if now is None else now
        st = self._state[spec.name]
        budget = self._budget(spec)
        out = []
        for win, max_burn in self.windows:
            in_win = [ok for (t, ok) in st.samples if now - t <= win]
            bad = sum(1 for ok in in_win if not ok)
            frac = bad / len(in_win) if in_win else 0.0
            out.append({"window_s": win, "max_burn": max_burn,
                        "samples": len(in_win),
                        "bad_fraction": frac,
                        "burn_rate": frac / budget})
        return out

    def check(self, now=None):
        """Sample every spec once; fire/refresh alerts. Returns the alerts
        fired by THIS check (possibly empty)."""
        now = self.clock() if now is None else now
        self.checks += 1
        fired = []
        for spec in self.specs:
            ok, value = spec.evaluate(self._tm(),
                                      rate_state=self._rate_state, now=now)
            st = self._state[spec.name]
            if ok is None:
                continue  # no data: no compliance sample either way
            st.samples.append((now, ok))
            st.last_value = value
            st.last_ok = ok
            burns = self.burn_rates(spec, now=now)
            burning = all(b["samples"] > 0
                          and b["burn_rate"] >= b["max_burn"]
                          for b in burns)
            if burning and not st.firing:
                st.firing = True
                st.alerts += 1
                alert = {
                    "ts": now,
                    "spec": spec.name,
                    "metric": spec.metric,
                    "value": value,
                    "threshold": spec.threshold,
                    "objective": spec.objective or self.objective,
                    "windows": burns,
                }
                self.alerts.append(alert)
                fired.append(alert)
                for sink in self.sinks:
                    try:
                        sink(alert)
                    except Exception as e:  # noqa: BLE001
                        warnings.warn(f"SLO alert sink {sink!r} failed: {e}",
                                      RuntimeWarning, stacklevel=2)
            elif not burning and st.firing:
                st.firing = False  # recovered: re-arm
        return fired

    def status(self):
        """Per-spec snapshot: last value/ok, compliance, burn, alert and
        firing state — the machine-readable side of :meth:`report`."""
        out = []
        for spec in self.specs:
            st = self._state[spec.name]
            n = len(st.samples)
            good = sum(1 for _, ok in st.samples if ok)
            out.append({
                "spec": spec.name,
                "value": st.last_value,
                "ok": st.last_ok,
                "samples": n,
                "compliance": good / n if n else None,
                "burn": self.burn_rates(spec),
                "firing": st.firing,
                "alerts": st.alerts,
            })
        return out

    def report(self, file=None):
        """Printable SLO table (printed and returned, mirroring
        ``telemetry.report``)."""
        lines = [f"{'SLO':<44} {'value':>12} {'compliance':>11} "
                 f"{'burn':>8} {'alerts':>7} {'state':>7}"]
        lines.append("-" * 94)
        for s in self.status():
            value = "-" if s["value"] is None else f"{s['value']:g}"
            comp = ("-" if s["compliance"] is None
                    else f"{100.0 * s['compliance']:.1f}%")
            burn = max((b["burn_rate"] for b in s["burn"]), default=0.0)
            state = "FIRING" if s["firing"] else "ok"
            lines.append(f"{s['spec']:<44} {value:>12} {comp:>11} "
                         f"{burn:>8.1f} {s['alerts']:>7} {state:>7}")
        lines.append(f"checks: {self.checks}  objective: {self.objective}  "
                     f"windows: " + ", ".join(
                         f"{int(w)}s@{b:g}x" for w, b in self.windows))
        table = "\n".join(lines)
        print(table, file=file)
        return table
