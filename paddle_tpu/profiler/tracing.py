"""Request-scoped tracing: trace/span ids threaded through serving + training.

The PR 2/5 telemetry answers *aggregate* questions (p95 TTFT, compile
counts); it cannot answer "why was *this* request's TTFT 800 ms". This
module mints a trace id per unit of work (a served request, a train epoch)
and records parent-linked spans for every stage it passes through:

* ``Scheduler.submit`` opens the request's root span and a ``queue`` child;
  admit closes the queue span and wraps the prefill; every decode tick
  records one ``decode_token`` span per *active request* (the batched
  ``serve_decode`` dispatch is shared — each request's span carries a
  ``decode_span`` attr linking to the shared one); evict closes the root.
* ``CompiledStep`` reports trace-context compile events: a call that traced
  while a span is current lands a ``compile`` child span, so the export
  shows exactly which request (or train step) paid which compile.
* ``hapi.Model.fit`` / ``GenerationEngine`` emit spans under the same API,
  so train steps and standalone ``generate()`` calls get trace context too.

Same zero-overhead contract as ``telemetry``: everything guards on a
module-level flag, ``span()``/``start_span()`` return shared no-op
singletons while disabled, and nothing times, locks or allocates until
:func:`enable` flips it.

Export: :meth:`Tracer.export_jsonl` (one span per line, ``trace``/``span``/
``parent`` ids + ns timestamps + attrs) and :meth:`Tracer.export_chrome`
(chrome://tracing / Perfetto ``trace_events``; pass
``include_telemetry=True`` to merge the telemetry phase timeline — both run
on the same ``perf_counter_ns`` clock, so a request's spans line up against
``data_wait``/``compile``/``dispatch`` without translation).
"""
from __future__ import annotations

import collections
import itertools
import json
import threading
import time

__all__ = [
    "Span",
    "Tracer",
    "get_tracer",
    "enable",
    "disable",
    "enabled",
    "reset",
    "span",
    "start_span",
    "current_span",
    "activate",
    "note_compile",
]

_ENABLED = False


def enabled():
    """Cheap global flag every instrumentation site guards on."""
    return _ENABLED


class _NullSpan:
    """Shared no-op stand-in while tracing is disabled: supports the whole
    Span surface (context manager, ``end``, ``set_attr``) so call sites
    never branch beyond the ``enabled()`` guard. Identity-testable for the
    zero-overhead tests."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self, end_ns=None):
        return self

    def set_attr(self, key, value):
        return self

    def __bool__(self):
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One timed, attributed node of a trace tree.

    ``start_span`` creates it open; ``end()`` (or leaving it as a context
    manager) closes it and files it with the tracer. Using a span as a
    context manager also makes it the *current* span for the thread, so
    children (and ``CompiledStep`` compile events) parent under it.
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ns",
                 "end_ns", "attrs", "tid", "_tracer", "_activated")

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 start_ns, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = None
        self.attrs = dict(attrs) if attrs else {}
        self.tid = threading.get_ident()
        self._tracer = tracer
        self._activated = False

    def set_attr(self, key, value):
        self.attrs[key] = value
        return self

    def end(self, end_ns=None):
        """Close the span (idempotent) and file it for export."""
        if self.end_ns is None:
            self.end_ns = end_ns if end_ns is not None \
                else time.perf_counter_ns()
            self._tracer._finish(self)
        return self

    @property
    def duration_s(self):
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e9

    def as_dict(self):
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_s": self.duration_s,
            "tid": self.tid,
            "attrs": dict(self.attrs),
        }

    # context-manager use: active (current) for the with-body, ended on exit
    def __enter__(self):
        self._tracer._push(self)
        self._activated = True
        return self

    def __exit__(self, *exc):
        if self._activated:
            self._tracer._pop(self)
            self._activated = False
        self.end()
        return False

    def __repr__(self):
        state = "open" if self.end_ns is None else f"{self.duration_s:.6f}s"
        return (f"<Span {self.name} trace={self.trace_id} "
                f"span={self.span_id} parent={self.parent_id} {state}>")


class _Activation:
    """Context manager making an existing (externally owned) span current
    without ending it — the scheduler holds request spans open across many
    ticks but needs them current only around the engine calls."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer, span):
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        if isinstance(self._span, Span):
            self._tracer._push(self._span)
        return self._span

    def __exit__(self, *exc):
        if isinstance(self._span, Span):
            self._tracer._pop(self._span)
        return False


class Tracer:
    """Process-wide span recorder. Finished spans live in a bounded ring
    (``ring_size``); ids are deterministic counters (``t0000000a`` /
    ``s0000002f``) so tests and diffs are stable run to run."""

    def __init__(self, ring_size=8192):
        self.ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._finished = collections.deque(maxlen=self.ring_size)
        self._trace_ids = itertools.count(1)
        self._span_ids = itertools.count(1)
        self._tls = threading.local()
        self._dropped = 0

    # -- id minting ---------------------------------------------------------
    def new_trace_id(self):
        with self._lock:
            return f"t{next(self._trace_ids):08x}"

    def _new_span_id(self):
        with self._lock:
            return f"s{next(self._span_ids):08x}"

    # -- current-span context (per thread) ----------------------------------
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _push(self, span):
        self._stack().append(span)

    def _pop(self, span):
        st = self._stack()
        if span in st:
            # tolerate out-of-order exits (generators, exceptions): pop
            # through to the named span rather than corrupting the stack
            while st and st[-1] is not span:
                st.pop()
            if st:
                st.pop()

    def current(self):
        st = self._stack()
        return st[-1] if st else None

    # -- span lifecycle -----------------------------------------------------
    def start_span(self, name, parent=None, trace_id=None, attrs=None,
                   start_ns=None):
        """Open a span. Parent resolution: explicit ``parent`` wins, else
        the thread's current span, else the span roots a new trace (or
        joins an explicit ``trace_id``)."""
        if parent is None and trace_id is None:
            parent = self.current()
        parent_id = None
        if isinstance(parent, Span):
            parent_id = parent.span_id
            if trace_id is None:
                trace_id = parent.trace_id
        if trace_id is None:
            trace_id = self.new_trace_id()
        return Span(self, name, trace_id, self._new_span_id(), parent_id,
                    start_ns if start_ns is not None
                    else time.perf_counter_ns(), attrs)

    def record(self, name, start_ns, end_ns, parent=None, trace_id=None,
               attrs=None):
        """Record an already-timed span (used for the shared decode
        interval fan-out and compile events)."""
        sp = self.start_span(name, parent=parent, trace_id=trace_id,
                             attrs=attrs, start_ns=start_ns)
        sp.end(end_ns)
        return sp

    def _finish(self, span):
        with self._lock:
            if len(self._finished) == self._finished.maxlen:
                self._dropped += 1
            self._finished.append(span)

    # -- read / export ------------------------------------------------------
    def spans(self, trace_id=None):
        """Finished spans (oldest first), optionally one trace's only."""
        with self._lock:
            out = list(self._finished)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def trace_ids(self):
        with self._lock:
            seen = {}
            for s in self._finished:
                seen.setdefault(s.trace_id, None)
        return list(seen)

    @property
    def dropped(self):
        """Spans evicted from the bounded ring (long-run safety valve)."""
        with self._lock:
            return self._dropped

    def export_jsonl(self, path_or_file, trace_id=None):
        """One span per line. Reconstructing a request is a filter+sort on
        the ``trace`` field — no joins needed."""
        spans = self.spans(trace_id)
        close = False
        f = path_or_file
        if isinstance(path_or_file, (str, bytes)):
            f = open(path_or_file, "w")
            close = True
        try:
            for s in spans:
                f.write(json.dumps(s.as_dict()) + "\n")
        finally:
            if close:
                f.close()
        return len(spans)

    def export_chrome(self, path, trace_id=None, include_telemetry=False):
        """Chrome ``trace_events`` JSON. Spans become complete (``X``)
        events with trace/span/parent ids in ``args``; with
        ``include_telemetry`` the telemetry phase timeline rides along as
        ``telemetry::<phase>`` events on the same clock."""
        events = []
        for s in self.spans(trace_id):
            end = s.end_ns if s.end_ns is not None else s.start_ns
            args = {"trace": s.trace_id, "span": s.span_id,
                    "parent": s.parent_id}
            args.update({k: v for k, v in s.attrs.items()
                         if isinstance(v, (str, int, float, bool))
                         or v is None})
            events.append({
                "name": s.name, "ph": "X", "cat": "trace",
                "ts": s.start_ns / 1e3, "dur": (end - s.start_ns) / 1e3,
                "pid": 0, "tid": s.tid, "args": args,
            })
        if include_telemetry:
            from . import telemetry as _telemetry

            for name, t0, t1, tid in _telemetry.get_telemetry().chrome_spans():
                events.append({
                    "name": f"telemetry::{name}", "ph": "X",
                    "cat": "telemetry", "ts": t0 / 1e3,
                    "dur": (t1 - t0) / 1e3, "pid": 0, "tid": tid,
                })
        events.sort(key=lambda e: e["ts"])
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return len(events)

    def reset(self):
        with self._lock:
            self._finished.clear()
            self._dropped = 0
            self._trace_ids = itertools.count(1)
            self._span_ids = itertools.count(1)
        self._tls = threading.local()


_TRACER = Tracer()


def get_tracer():
    return _TRACER


def enable(ring_size=None):
    """Turn tracing on (optionally resizing the finished-span ring).
    Returns the process-wide :class:`Tracer`."""
    global _ENABLED
    if ring_size is not None and int(ring_size) != _TRACER.ring_size:
        _TRACER.ring_size = int(ring_size)
        with _TRACER._lock:
            _TRACER._finished = collections.deque(
                _TRACER._finished, maxlen=_TRACER.ring_size)
    _ENABLED = True
    return _TRACER


def disable():
    """Turn tracing off. Recorded spans stay exportable until reset()."""
    global _ENABLED
    _ENABLED = False


def reset():
    _TRACER.reset()


def span(name, parent=None, trace_id=None, attrs=None):
    """Context-managed span: current for the body, ended on exit. Shared
    no-op singleton while disabled."""
    if not _ENABLED:
        return NULL_SPAN
    return _TRACER.start_span(name, parent=parent, trace_id=trace_id,
                              attrs=attrs)


def start_span(name, parent=None, trace_id=None, attrs=None):
    """Open a long-lived span (callers hold it across event-loop ticks and
    ``end()`` it themselves). No-op singleton while disabled."""
    if not _ENABLED:
        return NULL_SPAN
    return _TRACER.start_span(name, parent=parent, trace_id=trace_id,
                              attrs=attrs)


def current_span():
    """The thread's current span, or None (always None while disabled)."""
    if not _ENABLED:
        return None
    return _TRACER.current()


def activate(span_):
    """Make an existing open span current for a ``with`` body without
    ending it. Accepts (and ignores) the null span and None."""
    if not _ENABLED or not isinstance(span_, Span):
        return NULL_SPAN
    return _Activation(_TRACER, span_)


def note_compile(step_name, start_ns, end_ns, compile_index=None):
    """CompiledStep hook: a call that traced while a span was current files
    a ``compile`` child span — the export shows which request/train-step
    paid which (re)compile. No current span → the event is dropped (the
    aggregate telemetry compile counters still cover it)."""
    if not _ENABLED:
        return None
    cur = _TRACER.current()
    if cur is None:
        return None
    attrs = {"step": step_name}
    if compile_index is not None:
        attrs["compile_index"] = compile_index
    return _TRACER.record("compile", start_ns, end_ns, parent=cur,
                          attrs=attrs)
