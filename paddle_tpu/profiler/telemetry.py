"""Runtime telemetry for the async device pipeline.

PR 1 made the train loop asynchronous (``io.DeviceLoader`` prefetch, donated
compiled steps, deferred metric readback) but opaque: a slow step could be
data-wait, compilation, dispatch, or readback and nothing said which. This
module is the measurement substrate: a process-wide registry of counters,
gauges and time-histograms (extending :class:`~paddle_tpu.utils.log_writer.
Monitor`) plus a per-step *phase timeline* kept in a bounded ring buffer.

Phases (:data:`PHASES`):

  * ``data_wait`` — consumer blocked on the ``DeviceLoader`` hand-off queue
  * ``h2d_copy``  — host→device staging time in the stager thread
  * ``compile``   — a ``CompiledStep`` call that (re)traced/compiled
  * ``dispatch``  — a cached ``CompiledStep`` call (host enqueue time)
  * ``readback``  — blocking device→host fences (``AsyncMetricBuffer.drain``)

Zero overhead when disabled (the default): every instrumentation site guards
on the module-level :func:`enabled` bool and does *no* timing, allocation or
locking until :func:`enable` flips it. ``phase_span`` returns a shared no-op
singleton while disabled.

Instrumented producers run on two threads (the fit-loop consumer and the
``DeviceLoader`` stager); the registry is lock-protected and stager-side
phases are attributed to whichever step record is currently open — the
overlapped-pipeline reading of "this step's h2d time".

Export surfaces: :meth:`Telemetry.export_scalars` writes JSONL scalars
through a ``utils.log_writer.LogWriter`` (rendered by
``tools/telemetry_report.py``), :meth:`Telemetry.chrome_spans` yields spans
the :class:`~paddle_tpu.profiler.profiler.Profiler` merges into its
``ProfilerResult`` chrome trace, and :func:`report` prints the summary
table. ``hapi.callbacks.TelemetryLogger`` wires all of this into
``Model.fit``; ``tools/bench_common.telemetry_block`` embeds the summary
into the BENCH json.
"""
from __future__ import annotations

import collections
import threading
import time
import warnings

from ..utils.log_writer import Monitor

__all__ = [
    "PHASES",
    "Telemetry",
    "get_telemetry",
    "enable",
    "disable",
    "enabled",
    "reset",
    "phase_span",
    "step_begin",
    "step_end",
    "report",
    "summary",
    "serve_metrics",
]

#: canonical per-step pipeline phases, in pipeline order
PHASES = ("data_wait", "h2d_copy", "compile", "dispatch", "readback")

_ENABLED = False


def enabled():
    """Cheap global flag every instrumentation site guards on."""
    return _ENABLED


class _NullSpan:
    """Shared no-op context manager returned by ``phase_span`` when
    telemetry is disabled — identity-testable for zero-overhead checks."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _PhaseSpan:
    __slots__ = ("name", "_t0")

    def __init__(self, name):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            _TELEMETRY.add_phase(self.name, self._t0, time.perf_counter_ns())
            self._t0 = None
        return False


class _StepRecord:
    """One step's phase breakdown (seconds per phase)."""

    __slots__ = ("index", "start_ns", "end_ns", "phases")

    def __init__(self, index, start_ns):
        self.index = index
        self.start_ns = start_ns
        self.end_ns = start_ns
        self.phases = {}

    @property
    def wall_s(self):
        return max(self.end_ns - self.start_ns, 0) / 1e9

    def as_dict(self):
        return {"step": self.index, "wall_s": self.wall_s,
                "phases": dict(self.phases)}


class Telemetry(Monitor):
    """Process-wide counters + gauges + time-histograms + step timeline.

    Histograms reuse the inherited ``Monitor.add`` count/sum/min/max stats
    under ``phase.<name>`` keys; counters are monotonic, gauges hold the
    last value. The step timeline is a ``ring_size``-bounded deque of
    :class:`_StepRecord`; raw phase spans (for the chrome trace) live in a
    separate bounded deque so long runs can't grow memory unboundedly.
    """

    def __init__(self, ring_size=1024, recompile_warn_threshold=3):
        super().__init__()
        self.ring_size = int(ring_size)
        self.recompile_warn_threshold = int(recompile_warn_threshold)
        self._lock = threading.RLock()
        self._counters = {}
        self._gauges = {}
        self._ring = collections.deque(maxlen=self.ring_size)
        self._spans = collections.deque(maxlen=self.ring_size * 8)
        # bounded per-phase sample reservoirs for the p50/p95 columns
        # (Monitor.add only keeps count/sum/min/max)
        self._phase_samples = {}
        # same for observe() histograms (serve.ttft_s etc.): Monitor keeps
        # the EXACT running count/sum, the reservoir adds p50/p95
        self._hist_samples = {}
        self._current = None
        self._next_step = 0
        self._compiles = {}
        self._warned = set()
        # step-name -> declared executable-variant count: bucketed programs
        # (one prefill executable per length bucket) compile N times BY
        # DESIGN — declaring N keeps recompile_count a churn-only signal
        self._declared = {}

    # -- scalar registry ----------------------------------------------------
    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name, value):
        with self._lock:
            self._gauges[name] = float(value)

    def clear_gauge(self, name):
        """Drop one gauge (a finished producer retiring its stat)."""
        with self._lock:
            self._gauges.pop(name, None)

    def clear_gauges(self, prefix):
        """Drop every gauge under ``prefix`` — e.g. a shut-down
        ``DeviceLoader`` clearing its ``device_loader.*`` stats so the next
        ``report()`` doesn't show a stale queue depth."""
        with self._lock:
            for k in [k for k in self._gauges if k.startswith(prefix)]:
                del self._gauges[k]

    def observe(self, name, seconds):
        """Time-histogram sample: exact running count/sum/min/max (Monitor)
        plus a bounded reservoir for the p50/p95 columns."""
        with self._lock:
            self.add(name, seconds)
            self._hist_samples.setdefault(
                name, collections.deque(maxlen=2048)).append(float(seconds))

    def counters(self):
        with self._lock:
            return dict(self._counters)

    def gauges(self):
        with self._lock:
            return dict(self._gauges)

    # -- step timeline ------------------------------------------------------
    def _close_record(self, cur):
        """Append a phase-bearing record to the ring and publish its wall
        time as the ``step.time_s`` gauge (the per-rank step-time signal
        the elastic heartbeat forwards for straggler detection). Caller
        holds the lock."""
        self._ring.append(cur)
        self._gauges["step.time_s"] = cur.wall_s

    def step_begin(self):
        """Open a step record, closing (and keeping) any open one that saw
        phases. Loops call this before the iteration *and* at the end of
        each body so the next batch's data_wait lands in the next record."""
        with self._lock:
            cur = self._current
            if cur is not None and cur.phases:
                self._close_record(cur)
            self._current = _StepRecord(self._next_step,
                                        time.perf_counter_ns())
            self._next_step += 1

    def step_end(self):
        """Close the open record; empty (phase-less) records are dropped."""
        with self._lock:
            cur = self._current
            self._current = None
            if cur is not None and cur.phases:
                self._close_record(cur)

    def add_phase(self, name, start_ns, end_ns):
        """Record one phase span: histogram + chrome span + the open step."""
        secs = max(end_ns - start_ns, 0) / 1e9
        tid = threading.get_ident()
        with self._lock:
            self.add(f"phase.{name}", secs)
            self._phase_samples.setdefault(
                name, collections.deque(maxlen=2048)).append(secs)
            self._spans.append((name, start_ns, end_ns, tid))
            cur = self._current
            if cur is not None:
                cur.phases[name] = cur.phases.get(name, 0.0) + secs
                cur.end_ns = max(cur.end_ns, end_ns)

    def steps(self):
        """Closed step records, oldest first (bounded by ``ring_size``)."""
        with self._lock:
            return list(self._ring)

    # -- recompile detection ------------------------------------------------
    def note_compile(self, key, start_ns, end_ns):
        """A ``CompiledStep`` call that traced: count it per step-name and
        warn once when the same step recompiles beyond the threshold —
        recompilation churn means shape/dtype instability in the feed."""
        self.add_phase("compile", start_ns, end_ns)
        with self._lock:
            self._counters["compile.count"] = \
                self._counters.get("compile.count", 0) + 1
            n = self._compiles[key] = self._compiles.get(key, 0) + 1
            threshold = max(self.recompile_warn_threshold,
                            self._declared.get(key, 1))
            warn = n > threshold and key not in self._warned
            if warn:
                self._warned.add(key)
        if warn:
            warnings.warn(
                f"CompiledStep '{key}' compiled {n} times (threshold "
                f"{threshold}) — recompilation churn usually means batch "
                f"shapes/dtypes vary step to step; pad batches to fixed "
                f"shapes (drop_last=True) to keep one cached executable",
                RuntimeWarning, stacklevel=3)

    def compile_counts(self):
        with self._lock:
            return dict(self._compiles)

    def declare_variants(self, key, n):
        """Declare that step ``key`` legitimately compiles up to ``n``
        executables (one per length bucket / chunk width — the serving
        tier's compile-once-per-bucket design). ``recompile_count`` then
        counts only compiles BEYOND the declaration, so the sentinel can
        gate it at zero as a contract metric instead of absorbing the
        by-design bucket compiles as churn. Idempotent; the widest
        declaration wins."""
        with self._lock:
            self._declared[key] = max(self._declared.get(key, 1), int(n))

    def declared_variants(self):
        with self._lock:
            return dict(self._declared)

    @property
    def recompile_count(self):
        """Compilations beyond the declared variant count per step-name
        (the churn number; declarations default to 1)."""
        with self._lock:
            return sum(max(0, n - self._declared.get(k, 1))
                       for k, n in self._compiles.items())

    # -- export -------------------------------------------------------------
    @staticmethod
    def _percentile(xs, q):
        """Nearest-rank percentile over a sorted list."""
        if not xs:
            return 0.0
        idx = min(int(round(q * (len(xs) - 1))), len(xs) - 1)
        return xs[idx]

    def phase_stats(self):
        """{phase: {count, sum, min, max, mean, p50, p95}} from the
        histograms; p50/p95 come from a bounded (last 2048 samples)
        per-phase reservoir."""
        out = {}
        with self._lock:
            for key in self.names():
                if not key.startswith("phase."):
                    continue
                s = self.get(key)
                s["mean"] = s["sum"] / s["count"] if s.get("count") else 0.0
                name = key[len("phase."):]
                xs = sorted(self._phase_samples.get(name, ()))
                s["p50"] = self._percentile(xs, 0.50)
                s["p95"] = self._percentile(xs, 0.95)
                out[name] = s
        return out

    def _reservoir(self, name):
        """The bounded sample reservoir behind histogram ``name`` (phase
        histograms live under their short name). Caller holds the lock."""
        if name.startswith("phase."):
            return self._phase_samples.get(name[len("phase."):], ())
        return self._hist_samples.get(name, ())

    def histogram_stats(self, include_phases=False):
        """{name: {count, sum, min, max, mean, p50, p95}} for every
        ``observe()`` histogram — count/sum are the EXACT running totals
        (scraped rates stay correct), p50/p95 come from the bounded
        reservoirs. ``include_phases`` folds the ``phase.*`` timings in
        (the OpenMetrics exporter wants one flat view)."""
        out = {}
        with self._lock:
            for key in self.names():
                if key.startswith("phase.") and not include_phases:
                    continue
                s = self.get(key)
                s["mean"] = s["sum"] / s["count"] if s.get("count") else 0.0
                xs = sorted(self._reservoir(key))
                s["p50"] = self._percentile(xs, 0.50)
                s["p95"] = self._percentile(xs, 0.95)
                out[key] = s
        return out

    def stat(self, name, stat):
        """One scalar statistic of histogram ``name``: ``count``/``sum``/
        ``min``/``max``/``mean`` from the exact running totals, ``p<NN>``
        from the reservoir. Returns None when there are no samples (the
        SLO monitor skips the check rather than paging on nothing)."""
        with self._lock:
            s = self.get(name)
            if not s.get("count"):
                return None
            if stat == "mean":
                return s["sum"] / s["count"]
            if stat in s:
                return s[stat]
            if stat.startswith("p"):
                xs = sorted(self._reservoir(name))
                if not xs:
                    return None
                return self._percentile(xs, float(stat[1:]) / 100.0)
        raise ValueError(f"unknown histogram stat {stat!r}")

    def chrome_spans(self):
        """Buffered raw spans as (name, start_ns, end_ns, tid) tuples, on
        the same ``perf_counter_ns`` clock as the profiler's host events."""
        with self._lock:
            return list(self._spans)

    def summary(self):
        with self._lock:
            recs = list(self._ring)
            wall = sum(r.wall_s for r in recs)
            per_phase = {}
            for r in recs:
                for k, v in r.phases.items():
                    per_phase[k] = per_phase.get(k, 0.0) + v
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "phases": self.phase_stats(),
                "histograms": self.histogram_stats(),
                "steps_recorded": len(recs),
                "step_wall_s": wall,
                "step_phase_s": per_phase,
                "compiles": dict(self._compiles),
                "recompile_count": sum(
                    max(0, n - self._declared.get(k, 1))
                    for k, n in self._compiles.items()),
            }

    def export_scalars(self, writer, step=None):
        """Write the registry as JSONL scalars through a ``LogWriter``:
        ``telemetry/counter/<name>``, ``telemetry/gauge/<name>``,
        ``telemetry/phase/<name>/{total_s,count,mean_s}`` (cumulative), and
        ``telemetry/step/<phase>_s`` (the latest closed step record)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            stats = self.phase_stats()
            last = self._ring[-1] if self._ring else None
            last_phases = dict(last.phases) if last is not None else {}
        for k, v in counters.items():
            writer.add_scalar(f"telemetry/counter/{k}", v, step)
        for k, v in gauges.items():
            writer.add_scalar(f"telemetry/gauge/{k}", v, step)
        for name, s in stats.items():
            writer.add_scalar(f"telemetry/phase/{name}/total_s", s["sum"], step)
            writer.add_scalar(f"telemetry/phase/{name}/count", s["count"], step)
            writer.add_scalar(f"telemetry/phase/{name}/mean_s", s["mean"], step)
            writer.add_scalar(f"telemetry/phase/{name}/p50_s", s["p50"], step)
            writer.add_scalar(f"telemetry/phase/{name}/p95_s", s["p95"], step)
        for name, s in self.histogram_stats().items():
            writer.add_scalar(f"telemetry/hist/{name}/count", s["count"], step)
            writer.add_scalar(f"telemetry/hist/{name}/sum", s["sum"], step)
            writer.add_scalar(f"telemetry/hist/{name}/mean", s["mean"], step)
            writer.add_scalar(f"telemetry/hist/{name}/p50", s["p50"], step)
            writer.add_scalar(f"telemetry/hist/{name}/p95", s["p95"], step)
        for name, v in last_phases.items():
            writer.add_scalar(f"telemetry/step/{name}_s", v, step)

    #: gauge/counter prefixes rendered in the device-stats section of
    #: ``report()`` / ``tools/telemetry_report.py`` (devprof harvest)
    DEVICE_PREFIXES = ("hbm.", "comm.", "cost.", "pipeline.", "oom.")

    def report(self, file=None):
        """Phase-breakdown + counter summary table (printed and returned,
        mirroring ``Profiler.summary``)."""
        s = self.summary()
        lines = [f"{'Phase':<12} {'Count':>8} {'Total(s)':>12} "
                 f"{'Mean(ms)':>12} {'P50(ms)':>10} {'P95(ms)':>10} "
                 f"{'Frac(%)':>9}"]
        lines.append("-" * 79)
        wall = s["step_wall_s"]
        denom = wall or sum(st["sum"] for st in s["phases"].values()) or 1.0
        order = [p for p in PHASES if p in s["phases"]]
        order += [p for p in sorted(s["phases"]) if p not in PHASES]
        for name in order:
            st = s["phases"][name]
            lines.append(
                f"{name:<12} {st['count']:>8} {st['sum']:>12.4f} "
                f"{st['mean'] * 1e3:>12.3f} {st.get('p50', 0) * 1e3:>10.3f} "
                f"{st.get('p95', 0) * 1e3:>10.3f} "
                f"{100.0 * st['sum'] / denom:>9.2f}")
        lines.append("-" * 79)
        lines.append(f"steps recorded: {s['steps_recorded']}  "
                     f"(wall {wall:.4f} s over the ring window)")
        dev_prefixes = self.DEVICE_PREFIXES

        def _is_dev(k):
            return any(k.startswith(p) for p in dev_prefixes)

        plain_counters = {k: v for k, v in s["counters"].items()
                          if not _is_dev(k)}
        dev_counters = {k: v for k, v in s["counters"].items() if _is_dev(k)}
        plain_gauges = {k: v for k, v in s["gauges"].items()
                        if not _is_dev(k)}
        dev_gauges = {k: v for k, v in s["gauges"].items() if _is_dev(k)}
        if plain_counters:
            lines.append("counters:")
            for k in sorted(plain_counters):
                v = plain_counters[k]
                lines.append(f"  {k:<38} {v:g}" if isinstance(v, float)
                             else f"  {k:<38} {v}")
        if plain_gauges:
            lines.append("gauges:")
            for k in sorted(plain_gauges):
                lines.append(f"  {k:<38} {plain_gauges[k]:g}")
        if dev_gauges or dev_counters:
            # devprof harvest: HBM breakdown / collective bytes / pipeline
            def _human(n):
                n = float(n)
                for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
                    if abs(n) < 1024.0 or unit == "TiB":
                        return (f"{int(n)} B" if unit == "B"
                                else f"{n:.1f} {unit}")
                    n /= 1024.0

            lines.append("device stats:")
            for k in sorted(dev_gauges):
                v = dev_gauges[k]
                if k.endswith(("_bytes", ".bytes")):
                    lines.append(f"  {k:<38} {_human(v)}")
                else:
                    lines.append(f"  {k:<38} {v:g}")
            for k in sorted(dev_counters):
                v = dev_counters[k]
                if ".bytes." in k:
                    lines.append(f"  {k:<38} {_human(v)}")
                else:
                    lines.append(f"  {k:<38} {v:g}" if isinstance(v, float)
                                 else f"  {k:<38} {v}")
        if s["histograms"]:
            # observe() histograms (serve.ttft_s / serve.latency_s / ...):
            # exact count+sum so rates derived downstream are correct, and
            # the reservoir percentiles alongside
            lines.append(f"histograms: {'':<15} {'Count':>8} {'Sum':>12} "
                         f"{'Mean':>10} {'P50':>10} {'P95':>10}")
            for k in sorted(s["histograms"]):
                st = s["histograms"][k]
                lines.append(
                    f"  {k:<25} {st['count']:>8} {st['sum']:>12.4f} "
                    f"{st['mean']:>10.4f} {st['p50']:>10.4f} "
                    f"{st['p95']:>10.4f}")
        if s["compiles"]:
            lines.append(f"recompiles beyond first: {s['recompile_count']}")
            for k in sorted(s["compiles"]):
                lines.append(f"  compile[{k}] x{s['compiles'][k]}")
        table = "\n".join(lines)
        print(table, file=file)
        return table

    # -- lifecycle ----------------------------------------------------------
    def reset(self, name=None):
        """``reset()`` clears everything; ``reset(name)`` keeps Monitor's
        single-stat semantics for histogram keys."""
        with self._lock:
            if name is not None:
                return super().reset(name)
            super().reset()
            self._counters.clear()
            self._gauges.clear()
            self._ring.clear()
            self._spans.clear()
            self._phase_samples.clear()
            self._hist_samples.clear()
            self._current = None
            self._next_step = 0
            self._compiles.clear()
            self._warned.clear()


_TELEMETRY = Telemetry()


def get_telemetry():
    return _TELEMETRY


def enable(ring_size=None, recompile_warn_threshold=None):
    """Turn instrumentation on (optionally retuning the registry bounds).
    Returns the process-wide :class:`Telemetry` registry."""
    global _ENABLED
    if ring_size is not None and int(ring_size) != _TELEMETRY.ring_size:
        _TELEMETRY.ring_size = int(ring_size)
        with _TELEMETRY._lock:
            _TELEMETRY._ring = collections.deque(
                _TELEMETRY._ring, maxlen=_TELEMETRY.ring_size)
            _TELEMETRY._spans = collections.deque(
                _TELEMETRY._spans, maxlen=_TELEMETRY.ring_size * 8)
    if recompile_warn_threshold is not None:
        _TELEMETRY.recompile_warn_threshold = int(recompile_warn_threshold)
    _ENABLED = True
    return _TELEMETRY


def disable():
    """Turn instrumentation off. Collected data stays readable (``report``/
    ``summary``/``export_scalars``) until :func:`reset`."""
    global _ENABLED
    _ENABLED = False


def reset():
    _TELEMETRY.reset()


def phase_span(name):
    """Context manager timing one phase; shared no-op when disabled."""
    if not _ENABLED:
        return _NULL_SPAN
    return _PhaseSpan(name)


def step_begin():
    if _ENABLED:
        _TELEMETRY.step_begin()


def step_end():
    if _ENABLED:
        _TELEMETRY.step_end()


def serve_metrics(port=0, addr="127.0.0.1"):
    """Start the opt-in OpenMetrics ``/metrics`` endpoint over this
    registry (stdlib ``http.server``, ephemeral port by default). Returns
    the :class:`~paddle_tpu.profiler.export.MetricsServer` — read the
    bound port from ``.port``, stop with ``.close()``. Rendering happens
    per scrape in the handler thread; nothing touches the instrumented hot
    paths, so the zero-overhead-when-disabled contract holds."""
    from .export import serve_metrics as _serve

    return _serve(port=port, addr=addr, telemetry=_TELEMETRY)


def summary():
    return _TELEMETRY.summary()


def report(file=None):
    return _TELEMETRY.report(file=file)
