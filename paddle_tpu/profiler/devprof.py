"""Device-side observability: per-step cost/memory/comm ground truth.

PR 2's telemetry layer times the *host* side of a step (data_wait, h2d,
dispatch, readback) — it cannot say where HBM goes, how much of a step is
collective traffic vs compute, or why a run OOMed. XLA already knows all of
it per compiled executable: ``compiled.memory_analysis()`` breaks the peak
device allocation into argument/output/temp/generated-code segments and
``compiled.cost_analysis()`` reports FLOPs and bytes accessed. This module
closes the loop from that compiled-executable ground truth back into the
existing telemetry/JSONL/report pipeline.

Pieces:

* :func:`normalize_cost_analysis` — one shared shim over jax's unstable
  ``cost_analysis()`` return shape (newer jax: a list of per-computation
  dicts; older: a dict; unavailable: ``None``) used by ``cost_model``,
  ``tools/bench_common`` and this module.
* :class:`MemoryBreakdown` — the HBM peak decomposition from
  ``memory_analysis()`` (``peak = argument + output + temp +
  generated_code − alias``; the alias term is the donated input bytes the
  outputs reuse).
* **Collective attribution** (:class:`CollectiveStats`) from two
  complementary sources: :func:`collectives_from_jaxpr` walks the step's
  abstract trace (reusing :mod:`paddle_tpu.analysis`) for *explicit*
  collectives (the pipeline's ppermute/psum, ring attention, shard_map
  regions) and prices each with a ring-algorithm bytes-moved model;
  :func:`collectives_from_hlo` parses the *compiled* HLO for the full set
  including GSPMD-inserted ones (dp gradient all-reduce, TP activation
  psum, the MoE all_to_all pair), mapping each op's replica groups back to
  mesh axes. The HLO view is authoritative when available.
* :func:`device_report` / :meth:`CompiledStep.device_report` — harvest a
  :class:`DeviceCostReport` for a step (shape-only lowering: arguments are
  replaced by ``ShapeDtypeStruct`` so donated/consumed batches never need
  to be touched) and register it into the process telemetry registry as
  ``hbm.*`` / ``cost.*`` / ``comm.*`` gauges and per-axis
  ``comm.bytes.<axis>`` / ``comm.count.<axis>`` counters. With telemetry
  enabled, every ``CompiledStep`` auto-harvests once on its first compile
  (:func:`maybe_harvest_on_compile`).
* **Pipeline metrics** — :func:`pipeline_bubble_fraction` (the 1F1B
  schedule's analytic bubble ``(pp−1)/(M+pp−1)``) and
  :func:`bubble_from_spans` (bubble fraction from measured/synthetic
  per-rank microbatch spans); ``PipelinedModel`` publishes them as
  ``pipeline.*`` gauges. Per-rank step-time gauges ride the elastic
  heartbeat for straggler detection (``ElasticManager.stragglers``).
* **OOM forensics** — ``CompiledStep`` dispatch catches
  ``RESOURCE_EXHAUSTED`` and :func:`dump_oom_forensics` writes a ranked
  report (memory breakdown, donation status, batch/state shapes) to
  stderr (+ JSON at ``PADDLE_TPU_OOM_DUMP``) before re-raising.
"""
from __future__ import annotations

import json
import math
import os
import re
import sys
import warnings

import numpy as np

from . import telemetry as _telemetry

__all__ = [
    "normalize_cost_analysis",
    "MemoryBreakdown",
    "CollectiveStats",
    "DeviceCostReport",
    "device_report",
    "collectives_from_jaxpr",
    "collectives_from_hlo",
    "maybe_harvest_on_compile",
    "enable_auto_harvest",
    "auto_harvest_enabled",
    "get_report",
    "last_report",
    "reports",
    "clear_reports",
    "pipeline_bubble_fraction",
    "bubble_from_spans",
    "is_oom_error",
    "OOMForensics",
    "dump_oom_forensics",
    "last_oom_report",
]

#: env var naming a directory for OOM forensics JSON dumps
OOM_DUMP_ENV = "PADDLE_TPU_OOM_DUMP"


# ---------------------------------------------------------------------------
# cost_analysis normalization (shared with cost_model / tools/bench_common)
# ---------------------------------------------------------------------------

def normalize_cost_analysis(ca):
    """``compiled.cost_analysis()`` → one flat ``{key: float}`` dict.

    Newer jax returns a list of per-computation dicts, older jax a single
    dict, and unavailable backends ``None`` — numeric values are summed
    across computations, non-numeric entries dropped. Always returns a
    dict (possibly empty), so callers never branch on the shape again."""
    if isinstance(ca, dict):
        items = [ca]
    elif isinstance(ca, (list, tuple)):
        items = [d for d in ca if isinstance(d, dict)]
    else:
        return {}
    out = {}
    for d in items:
        for k, v in d.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = out.get(k, 0.0) + float(v)
    return out


# ---------------------------------------------------------------------------
# HBM breakdown
# ---------------------------------------------------------------------------

class MemoryBreakdown:
    """Peak device-memory decomposition of one compiled executable.

    ``peak_bytes = argument + output + temp + generated_code − alias``:
    the alias term is the donated argument bytes whose buffers the outputs
    reuse (counted once, not twice).

    ``alias_unavailable=True`` marks a breakdown whose alias term could not
    be trusted: an executable deserialized from the persistent compilation
    cache reports ``alias_size_in_bytes=0`` even when donation aliases
    buffers (observed on XLA:CPU), so ``peak_bytes`` double-counts the
    donated arguments. Consumers that *gate* on the peak
    (``analysis.crosscheck_mem``, ``tools/mem_report``) skip or annotate
    such a breakdown instead of mis-gating on it."""

    __slots__ = ("argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes", "alias_bytes", "alias_unavailable")

    def __init__(self, argument_bytes=0, output_bytes=0, temp_bytes=0,
                 generated_code_bytes=0, alias_bytes=0,
                 alias_unavailable=False):
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.generated_code_bytes = int(generated_code_bytes)
        self.alias_bytes = int(alias_bytes)
        self.alias_unavailable = bool(alias_unavailable)

    @property
    def peak_bytes(self):
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                + self.generated_code_bytes - self.alias_bytes)

    @classmethod
    def from_compiled(cls, compiled):
        """Harvest from ``compiled.memory_analysis()``; None when the
        backend doesn't expose it. Caveat: an executable deserialized from
        the persistent compilation cache can report ``alias_bytes=0`` even
        when donation aliases buffers (observed on XLA:CPU) — the peak is
        then a slight over-estimate."""
        try:
            ma = compiled.memory_analysis()
        except Exception:
            return None
        if ma is None:
            return None
        get = lambda k: int(getattr(ma, k, 0) or 0)  # noqa: E731
        return cls(
            argument_bytes=get("argument_size_in_bytes"),
            output_bytes=get("output_size_in_bytes"),
            temp_bytes=get("temp_size_in_bytes"),
            generated_code_bytes=get("generated_code_size_in_bytes"),
            alias_bytes=get("alias_size_in_bytes"),
        )

    def as_dict(self):
        return {
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes,
            "alias_unavailable": self.alias_unavailable,
        }

    def __repr__(self):
        return (f"MemoryBreakdown(peak={self.peak_bytes}, "
                f"arg={self.argument_bytes}, out={self.output_bytes}, "
                f"temp={self.temp_bytes}, "
                f"code={self.generated_code_bytes}, "
                f"alias={self.alias_bytes}"
                + (", alias_unavailable" if self.alias_unavailable else "")
                + ")")


# ---------------------------------------------------------------------------
# collective attribution
# ---------------------------------------------------------------------------

#: jaxpr collective primitives and their per-device bytes-moved factor as a
#: function of the participant count S (ring algorithms: an all-reduce is a
#: reduce-scatter + all-gather, each moving (S−1)/S of the buffer)
_COMM_FACTORS = {
    "psum": lambda s: 2.0 * (s - 1) / s,
    "psum2": lambda s: 2.0 * (s - 1) / s,
    "pmax": lambda s: 2.0 * (s - 1) / s,
    "pmin": lambda s: 2.0 * (s - 1) / s,
    "all_gather": lambda s: float(s - 1),          # input is the local shard
    "all_gather_invariant": lambda s: float(s - 1),
    "reduce_scatter": lambda s: (s - 1) / s,       # input is the full buffer
    "all_to_all": lambda s: (s - 1) / s,
    "ppermute": lambda s: 1.0,                     # full buffer, one hop
}

#: HLO collective ops → bytes-moved factor over the op's RESULT bytes
_HLO_FACTORS = {
    "all-reduce": lambda s: 2.0 * (s - 1) / s,     # result == operand
    "all-gather": lambda s: (s - 1) / s,           # result is the gathered buf
    "reduce-scatter": lambda s: float(s - 1),      # result is the local shard
    "all-to-all": lambda s: (s - 1) / s,
    "collective-permute": lambda s: 1.0,
    "collective-broadcast": lambda s: (s - 1) / s,  # root ships to s-1 peers
}

_HLO_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_HLO_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute"
    r"|collective-broadcast)"
    r"(-start)?\(")
_HLO_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_HLO_GROUPS_RE = re.compile(
    r"(?:replica_groups|source_target_pairs)=(\{\}|\{\{.*?\}\}|\[[0-9,]+\]"
    r"<=\[[0-9,]+\](?:T\([0-9,]+\))?)")


class CollectiveStats:
    """Per-mesh-axis collective accounting: count, bytes moved (per
    participating device), and a per-primitive breakdown."""

    def __init__(self):
        self.by_axis = {}  # axis label -> {count, bytes, prims: {prim: n}}

    def add(self, axis, prim, nbytes, count=1):
        st = self.by_axis.setdefault(str(axis), {"count": 0, "bytes": 0.0,
                                                 "prims": {}})
        st["count"] += int(count)
        st["bytes"] += float(nbytes)
        st["prims"][prim] = st["prims"].get(prim, 0) + int(count)

    @property
    def total_bytes(self):
        return sum(st["bytes"] for st in self.by_axis.values())

    @property
    def total_count(self):
        return sum(st["count"] for st in self.by_axis.values())

    def axes(self):
        return sorted(self.by_axis)

    def as_dict(self):
        return {axis: {"count": st["count"], "bytes": st["bytes"],
                       "prims": dict(st["prims"])}
                for axis, st in self.by_axis.items()}

    def __bool__(self):
        return bool(self.by_axis)

    def __repr__(self):
        inner = ", ".join(f"{a}: {st['count']}x/{st['bytes']:.0f}B"
                          for a, st in sorted(self.by_axis.items()))
        return f"CollectiveStats({inner})"


def _subjaxprs(v):
    from ..analysis.graph_lint import _subjaxprs as sub

    return sub(v)


def _aval_bytes(aval):
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


def _eqn_axis_names(eqn):
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", ())
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def collectives_from_jaxpr(closed_jaxpr):
    """Walk a step jaxpr for *explicit* collective primitives, tracking the
    mesh-axis sizes of enclosing ``shard_map`` regions to price each with
    the ring bytes-moved model. GSPMD-inserted collectives (sharding
    constraints on automatic axes) are invisible here — see
    :func:`collectives_from_hlo` for the compiled ground truth."""
    stats = CollectiveStats()

    def walk(jaxpr, axis_sizes):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                sizes = dict(axis_sizes)
                mesh = eqn.params.get("mesh")
                try:
                    sizes.update({str(k): int(v)
                                  for k, v in dict(mesh.shape).items()})
                except Exception:
                    pass
                for v in eqn.params.values():
                    for sub in _subjaxprs(v):
                        walk(sub, sizes)
                continue
            if prim in _COMM_FACTORS:
                axes = _eqn_axis_names(eqn)
                size = 1
                for a in axes:
                    size *= int(axis_sizes.get(a, 1))
                if size > 1:
                    nbytes = sum(_aval_bytes(v.aval) for v in eqn.invars)
                    moved = _COMM_FACTORS[prim](size) * nbytes
                    stats.add("+".join(axes), prim, moved)
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub, axis_sizes)

    walk(closed_jaxpr.jaxpr, {})
    return stats


def _decode_groups(text):
    """Decode an HLO ``replica_groups``/``source_target_pairs`` value into a
    list of partition-id groups. Handles the explicit ``{{0,1},{2,3}}`` form
    and the iota ``[G,S]<=[dims]T(perm)`` form; ``{}`` (all devices) returns
    None so the caller treats every partition as one group."""
    text = text.strip()
    if text.startswith("{"):
        inner = text[1:-1].strip()
        if not inner:
            return None  # empty => all participants
        groups = []
        for m in re.finditer(r"\{([0-9,\s]*)\}", inner):
            ids = [int(x) for x in m.group(1).replace(" ", "").split(",")
                   if x != ""]
            if ids:
                groups.append(ids)
        return groups or None
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", text)
    if not m:
        return None
    gshape = [int(x) for x in m.group(1).split(",")]
    dims = [int(x) for x in m.group(2).split(",")]
    perm = ([int(x) for x in m.group(3).split(",")] if m.group(3)
            else list(range(len(dims))))
    arr = np.arange(int(np.prod(dims))).reshape(dims).transpose(perm)
    arr = arr.reshape(gshape)
    return [list(map(int, row)) for row in arr]


def _axis_label(members, mesh_dims, axis_names, pairwise=False):
    """Mesh axes that vary across a replica group (or across
    source/target pairs), joined with '+' in mesh order."""
    coords = [np.unravel_index(int(g) % int(np.prod(mesh_dims)), mesh_dims)
              for g in members]
    if pairwise:
        varying = set()
        for i in range(0, len(coords) - 1, 2):
            a, b = coords[i], coords[i + 1]
            for d in range(len(mesh_dims)):
                if a[d] != b[d]:
                    varying.add(d)
    else:
        varying = {d for d in range(len(mesh_dims))
                   if len({c[d] for c in coords}) > 1}
    if not varying:
        return None
    return "+".join(axis_names[d] for d in sorted(varying))


def collectives_from_hlo(hlo_text, mesh=None):
    """Scan optimized HLO text for collective ops (including the
    GSPMD-inserted ones) and attribute each to the mesh axes its replica
    groups span. Partition ids are mapped to mesh coordinates assuming the
    executable's device assignment follows ``mesh.devices`` order (true for
    jitted NamedSharding programs). With no mesh, axes are labelled
    ``unmapped``. Bytes are per participating device, priced with the same
    ring model as the jaxpr walk."""
    stats = CollectiveStats()
    if mesh is not None:
        mesh_dims = tuple(int(s) for s in mesh.devices.shape)
        axis_names = tuple(str(a) for a in mesh.axis_names)
        n_part = int(np.prod(mesh_dims))
    else:
        mesh_dims = axis_names = None
        n_part = 0
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.search(line)
        if m is None:
            continue
        op, is_start = m.group(1), bool(m.group(2))
        head = line[:m.start()]
        shapes = []
        for dm in _HLO_SHAPE_RE.finditer(head):
            dt, dims = dm.group(1), dm.group(2)
            if dt not in _HLO_DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            shapes.append(n * _HLO_DTYPE_BYTES[dt])
        if not shapes:
            continue
        # async *-start ops repeat the buffer in their result tuple; take
        # the largest element instead of double counting
        nbytes = max(shapes) if is_start else sum(shapes)
        if is_start and op == "reduce-scatter":
            # For reduce-scatter the largest tuple element of the -start op is
            # the *input* (size x result), but _HLO_FACTORS prices the result
            # shard.  Rescale so sync and async forms price identically.
            gm0 = _HLO_GROUPS_RE.search(line)
            g0 = _decode_groups(gm0.group(1)) if gm0 else None
            sz = len(g0[0]) if g0 else (n_part or 2)
            if sz > 1:
                nbytes = nbytes // sz
        gm = _HLO_GROUPS_RE.search(line)
        groups = _decode_groups(gm.group(1)) if gm else None
        pairwise = op == "collective-permute"
        if groups is None:
            members = list(range(n_part)) if n_part else []
            size = len(members) or 2  # unknown world: assume pairs
        else:
            if pairwise:
                members = [g for grp in groups for g in grp]
                size = 2
            else:
                members = groups[0]
                size = max(len(g) for g in groups)
        if size <= 1:
            continue  # degenerate single-member groups: no traffic
        if mesh is not None and members:
            label = _axis_label(members, mesh_dims, axis_names,
                                pairwise=pairwise)
            if label is None:
                continue
        else:
            label = "unmapped"
        stats.add(label, op, _HLO_FACTORS[op](size) * nbytes)
    return stats


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0


class DeviceCostReport:
    """Compile-time cost/memory/comm ground truth for one compiled step.

    Attributes:
        name: step name.
        flops / bytes_accessed / optimal_seconds: XLA cost analysis of the
            whole executable (flops include remat recompute — the honest
            hardware-utilization number).
        memory: :class:`MemoryBreakdown` or None.
        collectives: authoritative per-axis :class:`CollectiveStats`
            (compiled-HLO view when available, else the jaxpr view).
        collectives_traced: the jaxpr (explicit-collective) view, kept for
            cross-checking.
        comm_source: ``"hlo"`` | ``"jaxpr"`` | ``"none"``.
    """

    def __init__(self, name, flops=0.0, bytes_accessed=0.0,
                 optimal_seconds=0.0, memory=None, collectives=None,
                 collectives_traced=None, comm_source="none", cost_raw=None):
        self.name = name
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.optimal_seconds = float(optimal_seconds)
        self.memory = memory
        self.collectives = collectives or CollectiveStats()
        self.collectives_traced = collectives_traced or CollectiveStats()
        self.comm_source = comm_source
        self.cost_raw = dict(cost_raw or {})

    @property
    def comm_bytes(self):
        """Interconnect bytes moved per device per step (authoritative)."""
        return self.collectives.total_bytes

    @property
    def comm_fraction(self):
        """Share of the step's memory traffic that crosses the
        interconnect: ``comm_bytes / (comm_bytes + bytes_accessed)``.
        0.0 on a single device; → 1.0 for pure-communication programs."""
        denom = self.comm_bytes + self.bytes_accessed
        return self.comm_bytes / denom if denom > 0 else 0.0

    def as_dict(self):
        return {
            "name": self.name,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "optimal_seconds": self.optimal_seconds,
            "memory": self.memory.as_dict() if self.memory else None,
            "collectives": self.collectives.as_dict(),
            "collectives_traced": self.collectives_traced.as_dict(),
            "comm_source": self.comm_source,
            "comm_bytes": self.comm_bytes,
            "comm_fraction": self.comm_fraction,
        }

    def register(self, tm=None):
        """Publish into the telemetry registry: ``hbm.*`` / ``cost.*`` /
        ``comm.*`` gauges plus per-axis ``comm.{bytes,count}.<axis>``
        counters (counters accumulate across harvested steps)."""
        tm = tm or _telemetry.get_telemetry()
        if self.memory is not None:
            for k, v in self.memory.as_dict().items():
                tm.set_gauge(f"hbm.{k}", v)
        tm.set_gauge("cost.flops", self.flops)
        tm.set_gauge("cost.bytes_accessed", self.bytes_accessed)
        if self.optimal_seconds:
            tm.set_gauge("cost.optimal_seconds", self.optimal_seconds)
        tm.set_gauge("comm.bytes", self.comm_bytes)
        tm.set_gauge("comm.fraction", self.comm_fraction)
        for axis, st in self.collectives.by_axis.items():
            tm.inc(f"comm.bytes.{axis}", int(st["bytes"]))
            tm.inc(f"comm.count.{axis}", int(st["count"]))
        return self

    def table(self):
        """Human-readable summary (mirrors ``telemetry.report`` style)."""
        lines = [f"device cost report — {self.name}"]
        lines.append(f"  flops          {self.flops:,.0f}")
        lines.append(f"  bytes accessed {_fmt_bytes(self.bytes_accessed)}")
        if self.optimal_seconds:
            lines.append(f"  optimal time   {self.optimal_seconds:.6f} s")
        if self.memory is not None:
            md = self.memory.as_dict()
            peak = md.pop("peak_bytes") or 1
            alias = md.pop("alias_bytes")
            alias_unavailable = md.pop("alias_unavailable", False)
            lines.append(f"  hbm peak       {_fmt_bytes(peak)}")
            for k, v in sorted(md.items(), key=lambda kv: -kv[1]):
                if v:
                    lines.append(f"    {k:<22} {_fmt_bytes(v):>12} "
                                 f"({100.0 * v / peak:5.1f}%)")
            if alias:
                lines.append(f"    {'alias_bytes (reused)':<22} "
                             f"{'-' + _fmt_bytes(alias):>12}")
            if alias_unavailable:
                lines.append("    alias term unavailable (persistent-cache "
                             "executable): peak over-counts donated args")
        if self.collectives:
            lines.append(f"  collectives ({self.comm_source}): "
                         f"{_fmt_bytes(self.comm_bytes)} moved/device, "
                         f"comm_fraction {self.comm_fraction:.4f}")
            for axis in self.collectives.axes():
                st = self.collectives.by_axis[axis]
                prims = ",".join(f"{p}x{n}" for p, n in
                                 sorted(st["prims"].items()))
                lines.append(f"    axis {axis:<12} {st['count']:>4} ops "
                             f"{_fmt_bytes(st['bytes']):>12}  [{prims}]")
        else:
            lines.append("  collectives: none (single device)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# harvesting
# ---------------------------------------------------------------------------

_REPORTS = {}
_LAST_NAME = None
_AUTO = True


def enable_auto_harvest(flag=True):
    """Gate the once-per-step auto-harvest ``CompiledStep`` runs on its
    first compile while telemetry is enabled (on by default)."""
    global _AUTO
    _AUTO = bool(flag)


def auto_harvest_enabled():
    return _AUTO


def get_report(name):
    """Harvested :class:`DeviceCostReport` by step name, or None."""
    return _REPORTS.get(name)


def last_report():
    """The most recently harvested report (or None)."""
    return _REPORTS.get(_LAST_NAME) if _LAST_NAME else None


def reports():
    return dict(_REPORTS)


def clear_reports():
    global _LAST_NAME
    _REPORTS.clear()
    _LAST_NAME = None


def _lower_isolated(step, args, kwargs):
    """Lower the step through a FRESH ``jax.jit`` instance. Going through
    ``step.lower`` (i.e. ``step._jitted``) would populate the step's own
    tracing cache with the harvest-time state signature — and a state
    whose pytree evolves across calls (the lazy-accumulator pattern the
    graph lint exists to catch) would then dispatch its next call from the
    harvest's cache entry without visibly re-tracing, corrupting the
    compile/recompile telemetry contract. XLA's compilation cache still
    dedupes the underlying executable."""
    import jax

    donate = (0,) if step.donate_state else ()
    donate = donate + (1,)
    # the lambda gives the harvest its own function identity: jax's trace
    # cache is keyed on the wrapped callable, so jitting step._pure
    # directly would still share (and pre-populate) the step's entries
    pure = step._pure
    jitted = jax.jit(lambda *a: pure(*a), donate_argnums=donate,
                     static_argnums=(3,))
    state = step.spec.snapshot()
    dyn_donated, dyn_kept, static = step._prepare(args, kwargs)
    try:
        return jitted.lower(state, dyn_donated, dyn_kept, static)
    finally:
        # pure()'s own finally restores the pre-trace state; lazily-born
        # leaves would be tracers there (see analysis.trace_step) — the
        # wholesale re-install below keeps framework state eager
        step.spec.install(state)
        step.spec.clear_grads()


def _shape_only(tree):
    """Replace array-like leaves with ``ShapeDtypeStruct`` (keeping the
    sharding, so the lowered program sees the same SPMD partitioning) —
    lowering never touches real, possibly-donated buffers."""
    import jax

    from ..framework.tensor import Tensor

    def leaf(x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, (jax.Array, np.ndarray, np.generic)):
            sharding = getattr(x, "sharding", None)
            try:
                return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                            sharding=sharding)
            except Exception:
                return jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def _infer_mesh(step, args, kwargs):
    """Best-effort mesh discovery: a NamedSharding on any argument or
    state leaf (size > 1)."""
    import jax
    from jax.sharding import NamedSharding

    from ..framework.tensor import Tensor

    def scan(tree):
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, Tensor):
                leaf = leaf._value
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh.size > 1:
                return sh.mesh
        return None

    mesh = scan((args, kwargs))
    if mesh is None:
        try:
            mesh = scan(step.spec.snapshot())
        except Exception:
            mesh = None
    return mesh


def device_report(step, *args, mesh=None, name=None, register=None, **kwargs):
    """Harvest a :class:`DeviceCostReport` for ``step`` against the example
    batch (real arrays, Tensors, or ``ShapeDtypeStruct``s — arrays are
    reduced to shapes first, so donated batches are safe to pass).

    Lowers and compiles the step (XLA dedupes against its compile cache),
    reads ``memory_analysis``/``cost_analysis``, attributes collectives
    from the compiled HLO (falling back to the jaxpr walk when HLO text is
    unavailable), stores the report in the process registry
    (:func:`get_report`) and — when telemetry is enabled, or
    ``register=True`` — publishes the ``hbm.*``/``cost.*``/``comm.*``
    telemetry scalars."""
    global _LAST_NAME

    from ..jit.functionalize import CompiledStep

    if not isinstance(step, CompiledStep):
        step = CompiledStep(step, stateful=(), donate_state=False)
    sds_args, sds_kwargs = _shape_only((args, kwargs))
    if mesh is None:
        mesh = _infer_mesh(step, args, kwargs)

    traced = CollectiveStats()
    try:
        from .. import analysis

        graph = analysis.trace_step(step, *sds_args, **sds_kwargs)
        traced = collectives_from_jaxpr(graph.closed_jaxpr)
    except Exception as e:  # noqa: BLE001 - advisory view only
        warnings.warn(f"devprof jaxpr collective walk failed on "
                      f"'{step.name}': {e!r}", RuntimeWarning)

    lowered = _lower_isolated(step, sds_args, sds_kwargs)
    compiled = lowered.compile()
    memory = MemoryBreakdown.from_compiled(compiled)
    if (memory is not None and memory.alias_bytes == 0
            and (getattr(step, "donate_state", False)
                 or getattr(step, "donate_inputs", False))):
        # the step donates buffers, yet the executable reports zero alias
        # bytes: the persistent-cache deserialization path loses the alias
        # table (XLA:CPU) — flag it so peak-gating consumers skip this one
        memory.alias_unavailable = True
    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        cost = {}

    hlo_stats = None
    try:
        hlo_stats = collectives_from_hlo(compiled.as_text(), mesh=mesh)
    except Exception as e:  # noqa: BLE001 - fall back to the jaxpr view
        warnings.warn(f"devprof HLO collective scan failed on "
                      f"'{step.name}': {e!r}", RuntimeWarning)
    if hlo_stats is not None and (hlo_stats or not traced):
        coll, source = hlo_stats, "hlo"
    elif traced:
        coll, source = traced, "jaxpr"
    else:
        coll, source = CollectiveStats(), "none"

    rep = DeviceCostReport(
        name=name or step.name,
        flops=cost.get("flops", 0.0),
        bytes_accessed=cost.get("bytes accessed", 0.0),
        optimal_seconds=cost.get("optimal_seconds", 0.0),
        memory=memory,
        collectives=coll,
        collectives_traced=traced,
        comm_source=source,
        cost_raw=cost,
    )
    _REPORTS[rep.name] = rep
    _LAST_NAME = rep.name
    if register is None:
        register = _telemetry.enabled()
    if register:
        rep.register()
    return rep


def maybe_harvest_on_compile(step, args, kwargs):
    """Once-per-step harvest hook ``CompiledStep.__call__`` fires after a
    traced call while telemetry is enabled. Never raises — observability
    must not take down a training run."""
    if not (_AUTO and _telemetry.enabled()):
        return None
    if getattr(step, "_devprof_done", False):
        return None
    try:
        step._devprof_done = True
    except Exception:
        return None
    try:
        return device_report(step, *args, **kwargs)
    except Exception as e:  # noqa: BLE001 - advisory pass only
        warnings.warn(f"devprof harvest failed on '{step.name}': {e!r}",
                      RuntimeWarning)
        return None


# ---------------------------------------------------------------------------
# pipeline / straggler metrics
# ---------------------------------------------------------------------------

def pipeline_bubble_fraction(num_microbatches, pp_degree):
    """Analytic 1F1B/GPipe schedule bubble: with M microbatches over pp
    stages the scan runs ``T = M + pp − 1`` ticks of which ``pp − 1`` are
    ramp-up/drain bubbles on every stage → ``(pp−1)/(M+pp−1)``."""
    m, pp = int(num_microbatches), int(pp_degree)
    if m <= 0 or pp <= 1:
        return 0.0
    return (pp - 1) / float(m + pp - 1)


def bubble_from_spans(spans):
    """Bubble fraction from measured (or synthetic) per-rank microbatch
    phase spans.

    Args:
        spans: ``{rank: [(start, end), ...]}`` or an iterable of
            ``(rank, start, end)`` tuples, on any consistent clock.

    Returns ``{"window_s", "per_rank": {rank: bubble}, "bubble_fraction"}``
    where each rank's bubble is the fraction of the global busy window
    it spent idle, and ``bubble_fraction`` is their mean."""
    if not isinstance(spans, dict):
        folded = {}
        for rank, t0, t1 in spans:
            folded.setdefault(rank, []).append((t0, t1))
        spans = folded
    all_spans = [s for ss in spans.values() for s in ss]
    if not all_spans:
        return {"window_s": 0.0, "per_rank": {}, "bubble_fraction": 0.0}
    t0 = min(s[0] for s in all_spans)
    t1 = max(s[1] for s in all_spans)
    window = max(t1 - t0, 0.0)
    per_rank = {}
    for rank, ss in spans.items():
        busy = sum(max(e - b, 0.0) for b, e in ss)
        per_rank[rank] = (max(1.0 - busy / window, 0.0) if window > 0
                          else 0.0)
    frac = (math.fsum(per_rank.values()) / len(per_rank)) if per_rank else 0.0
    return {"window_s": window, "per_rank": per_rank,
            "bubble_fraction": frac}


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_LAST_OOM = None


def is_oom_error(err):
    """Does this dispatch-time exception look like a device OOM? XLA
    surfaces them as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...``; the
    fault-injection stand-in carries the same marker."""
    return "RESOURCE_EXHAUSTED" in str(err)


def _leaf_meta(tree, prefix):
    """Flatten a pytree into (path, shape, dtype, nbytes) rows, largest
    first. Reads only array *metadata* — safe on donated/deleted buffers."""
    import jax

    from ..framework.tensor import Tensor

    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if isinstance(leaf, Tensor):
            leaf = leaf._value
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        try:
            dtype = np.dtype(getattr(leaf, "dtype", np.float32))
            nbytes = int(np.prod(shape)) * dtype.itemsize
        except Exception:
            continue
        rows.append({
            "path": prefix + jax.tree_util.keystr(tuple(path)),
            "shape": tuple(int(s) for s in shape),
            "dtype": str(dtype),
            "nbytes": nbytes,
        })
    rows.sort(key=lambda r: -r["nbytes"])
    return rows


class OOMForensics:
    """Structured post-mortem of a ``RESOURCE_EXHAUSTED`` dispatch: the
    compiled memory breakdown (when a harvest exists), donation status,
    and the batch/state arrays ranked by size."""

    def __init__(self, step_name, error, memory=None, donation=None,
                 batch=None, state=None, collectives=None):
        self.step_name = step_name
        self.error = str(error)
        self.memory = memory
        self.donation = dict(donation or {})
        self.batch = list(batch or [])
        self.state = list(state or [])
        self.collectives = dict(collectives or {})

    def as_dict(self):
        return {
            "step": self.step_name,
            "error": self.error,
            "memory": (self.memory.as_dict()
                       if isinstance(self.memory, MemoryBreakdown)
                       else self.memory),
            "donation": self.donation,
            "batch": self.batch,
            "state": self.state,
            "collectives": self.collectives,
        }

    @classmethod
    def from_dict(cls, d):
        mem = d.get("memory")
        if isinstance(mem, dict):
            mem = MemoryBreakdown(
                argument_bytes=mem.get("argument_bytes", 0),
                output_bytes=mem.get("output_bytes", 0),
                temp_bytes=mem.get("temp_bytes", 0),
                generated_code_bytes=mem.get("generated_code_bytes", 0),
                alias_bytes=mem.get("alias_bytes", 0),
                alias_unavailable=mem.get("alias_unavailable", False))
        return cls(d.get("step", "?"), d.get("error", ""), memory=mem,
                   donation=d.get("donation"), batch=d.get("batch"),
                   state=d.get("state"), collectives=d.get("collectives"))

    def report(self):
        lines = [f"OOM forensics — step '{self.step_name}' hit "
                 f"RESOURCE_EXHAUSTED at dispatch"]
        lines.append(f"  error: {self.error.splitlines()[0][:200]}")
        if isinstance(self.memory, MemoryBreakdown):
            md = self.memory.as_dict()
            peak = md.pop("peak_bytes") or 1
            alias = md.pop("alias_bytes")
            md.pop("alias_unavailable", None)
            lines.append(f"  compiled memory breakdown "
                         f"(peak {_fmt_bytes(peak)}):")
            for k, v in sorted(md.items(), key=lambda kv: -kv[1]):
                if v:
                    lines.append(f"    {k:<22} {_fmt_bytes(v):>12} "
                                 f"({100.0 * v / peak:5.1f}%)")
            if alias:
                lines.append(f"    {'alias_bytes (reused)':<22} "
                             f"{'-' + _fmt_bytes(alias):>12}")
        else:
            lines.append("  compiled memory breakdown: unavailable "
                         "(step failed before/without a harvest)")
        don = self.donation
        lines.append(f"  donation: donate_state={don.get('donate_state')} "
                     f"donate_inputs={don.get('donate_inputs')}"
                     + (f" paths={don.get('donate_paths')}"
                        if don.get("donate_paths") else ""))
        if not don.get("donate_inputs"):
            lines.append("    hint: staged single-use batches can hand "
                         "their HBM back via donate_inputs=True")
        if self.batch:
            lines.append("  batch arrays (largest first):")
            for r in self.batch[:8]:
                lines.append(f"    {r['path']:<28} {str(r['shape']):<20} "
                             f"{r['dtype']:<10} {_fmt_bytes(r['nbytes'])}")
        if self.state:
            lines.append("  largest state arrays:")
            for r in self.state[:10]:
                lines.append(f"    {r['path']:<44} "
                             f"{_fmt_bytes(r['nbytes'])}")
        return "\n".join(lines)


def last_oom_report():
    """The most recent :class:`OOMForensics` (or None)."""
    return _LAST_OOM


def dump_oom_forensics(step, err, args, kwargs, file=None):
    """Build, print (stderr) and remember the forensics for an OOM raised
    at ``step``'s dispatch; with ``PADDLE_TPU_OOM_DUMP=<dir>`` also writes
    ``oom_<step>.json`` there. The caller re-raises the original error."""
    global _LAST_OOM

    rep = _REPORTS.get(getattr(step, "name", None))
    donation = {
        "donate_state": bool(getattr(step, "donate_state", False)),
        "donate_inputs": bool(getattr(step, "donate_inputs", False)),
        "donate_paths": list(getattr(step, "_donate_paths", None) or []),
    }
    try:
        state_rows = _leaf_meta(step.spec.snapshot(), "state")[:16]
    except Exception:
        state_rows = []
    fo = OOMForensics(
        step_name=getattr(step, "name", "?"),
        error=err,
        memory=rep.memory if rep is not None else None,
        donation=donation,
        batch=_leaf_meta((args, kwargs or {}), "args")[:16],
        state=state_rows,
        collectives=rep.collectives.as_dict() if rep is not None else {},
    )
    _LAST_OOM = fo
    print(fo.report(), file=file or sys.stderr)
    if _telemetry.enabled():
        _telemetry.get_telemetry().inc("oom.count")
    dump_dir = os.environ.get(OOM_DUMP_ENV, "").strip()
    if dump_dir:
        try:
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(dump_dir, f"oom_{fo.step_name}.json")
            with open(path, "w") as f:
                json.dump(fo.as_dict(), f, indent=1)
        except Exception as e:  # noqa: BLE001 - forensics must not mask OOM
            print(f"OOM forensics dump to {dump_dir} failed: {e!r}",
                  file=sys.stderr)
    return fo
