"""paddle.profiler (reference ``python/paddle/profiler/__init__.py``)."""
from . import devprof  # noqa: F401
from . import export  # noqa: F401
from . import slo  # noqa: F401
from . import telemetry  # noqa: F401
from . import tracing  # noqa: F401
from .profiler import (  # noqa: F401
    Profiler,
    ProfilerState,
    ProfilerTarget,
    RecordEvent,
    SortedKeys,
    export_chrome_tracing,
    export_protobuf,
    get_profiler,
    in_profiler_mode,
    load_profiler_result,
    make_scheduler,
    wrap_optimizers,
)

__all__ = [
    "ProfilerState", "ProfilerTarget", "make_scheduler",
    "export_chrome_tracing", "export_protobuf", "Profiler", "RecordEvent",
    "load_profiler_result", "SortedKeys", "telemetry", "devprof",
    "tracing", "export", "slo",
]
