"""paddle.profiler — TPU-native profiling.

Reference surface: ``python/paddle/profiler/profiler.py:271`` (class
Profiler, scheduler states ``:34``, ``make_scheduler:71``,
``export_chrome_tracing:158``) and ``profiler/utils.py:34`` (RecordEvent).

TPU-native redesign: the reference layers a host tracer + CUPTI device
tracer feeding an event tree (``platform/profiler/host_tracer.cc``,
``cuda_tracer.cc``, ``chrometracing_logger.cc``). On TPU the device side is
XLA's own XPlane profiler — ``jax.profiler.start_trace`` captures device HLO
timelines viewable in TensorBoard/Perfetto — so this module keeps:

  * a host event recorder (RecordEvent ≙ platform::RecordEvent) whose spans
    also become ``jax.profiler.TraceAnnotation``s, stitching python-level
    names into the XPlane device trace;
  * the reference's scheduler-state machine (CLOSED/READY/RECORD/
    RECORD_AND_RETURN) driving when the XPlane capture is on;
  * chrome-trace export of the host spans + summary tables.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from enum import Enum

__all__ = [
    "ProfilerState",
    "ProfilerTarget",
    "make_scheduler",
    "export_chrome_tracing",
    "export_protobuf",
    "Profiler",
    "RecordEvent",
    "load_profiler_result",
    "SortedKeys",
    "in_profiler_mode",
    "wrap_optimizers",
]


class ProfilerState(Enum):
    """Reference ``profiler.py:34`` — profiling on/off state per step."""

    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    """Reference ``profiler.py:54`` (CPU/GPU/MLU) — here CPU (host spans)
    and TPU (XPlane device capture); GPU accepted as an alias for device."""

    CPU = 0
    GPU = 1
    TPU = 2


class SortedKeys(Enum):
    """Reference ``profiler_statistic.py`` SortedKeys."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """State machine over step numbers (reference ``profiler.py:71``):
    skip_first CLOSED steps, then cycles of closed→ready→record, the last
    record step returning RECORD_AND_RETURN."""
    period = closed + ready + record

    def getScheduleState(step: int) -> ProfilerState:
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat > 0 and step // period >= repeat:
            return ProfilerState.CLOSED
        pos = step % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return getScheduleState


def _default_state_scheduler(step: int):
    return ProfilerState.RECORD


# ---------------------------------------------------------------------------
# host event recording (≙ platform/profiler/host_tracer.cc)
# ---------------------------------------------------------------------------

_local = threading.local()
_ACTIVE_PROFILERS = []

# native RecordEvent sink (core/native/host_tracer.cc ≙ the reference's C++
# host_tracer): one ctypes call per span instead of python object churn.
# Drained into _HostEvents at finalize; python path is the fallback.
_native_state = {"lib": None, "active": False, "owner": None}
_TYPE_SEP = "\x1f"


def _start_native_tracer(owner):
    from .. import core

    lib = core.load_native()
    if lib is not None:
        lib.pt_tracer_start(1_000_000)
        _native_state.update(lib=lib, active=True, owner=owner)


def _drain_native_tracer(events):
    import ctypes

    lib = _native_state["lib"]
    if not _native_state["active"] or lib is None:
        return
    lib.pt_tracer_stop()
    n = int(lib.pt_tracer_count())
    if n:
        buflen = 160 * n + 1024
        buf = ctypes.create_string_buffer(buflen)
        rc = int(lib.pt_tracer_dump(buf, buflen))
        if rc < 0:
            buf = ctypes.create_string_buffer(-rc)
            rc = int(lib.pt_tracer_dump(buf, -rc))
        for line in buf.raw[:max(rc, 0)].decode(errors="replace").splitlines():
            try:
                name, s, e, tid = line.rsplit("\t", 3)
            except ValueError:
                continue
            etype = "PythonUserDefined"
            if _TYPE_SEP in name:
                name, etype = name.rsplit(_TYPE_SEP, 1)
            events.append(_HostEvent(name, etype, int(tid), int(s), int(e)))
        lib.pt_tracer_clear()
    _native_state["active"] = False
    _native_state["owner"] = None


def in_profiler_mode():
    return bool(_ACTIVE_PROFILERS)


class _HostEvent:
    __slots__ = ("name", "event_type", "tid", "start_ns", "end_ns")

    def __init__(self, name, event_type, tid, start_ns, end_ns):
        self.name = name
        self.event_type = event_type
        self.tid = tid
        self.start_ns = start_ns
        self.end_ns = end_ns


class RecordEvent:
    """Host span annotation (reference ``profiler/utils.py:34`` RecordEvent ≙
    C++ ``platform::RecordEvent``). Also emitted as a
    ``jax.profiler.TraceAnnotation`` so the name shows up inside the XPlane
    device trace."""

    def __init__(self, name: str, event_type: str = "PythonUserDefined"):
        self.name = name
        self.event_type = event_type
        self._start_ns = None
        self._jax_ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self.end()

    def __call__(self, func):
        import functools

        @functools.wraps(func)
        def inner(*args, **kwargs):
            with RecordEvent(self.name, self.event_type):
                return func(*args, **kwargs)

        return inner

    def begin(self):
        if not in_profiler_mode():
            return
        try:
            import jax.profiler as jp

            self._jax_ann = jp.TraceAnnotation(self.name)
            self._jax_ann.__enter__()
        except Exception:
            self._jax_ann = None
        self._start_ns = time.perf_counter_ns()

    def end(self):
        if self._start_ns is None:
            return
        end_ns = time.perf_counter_ns()
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        start_ns = self._start_ns
        self._start_ns = None
        handled = None
        if _native_state["active"]:
            tag = f"{self.name}{_TYPE_SEP}{self.event_type}".encode()
            if _native_state["lib"].pt_tracer_record(tag, start_ns, end_ns) == 0:
                handled = _native_state["owner"]
        if handled is not None and all(p is handled for p in _ACTIVE_PROFILERS):
            return
        ev = _HostEvent(self.name, self.event_type, threading.get_ident(),
                        start_ns, end_ns)
        for prof in _ACTIVE_PROFILERS:
            if prof is not handled:  # the owner drains the native buffer
                prof._record(ev)


def wrap_optimizers():
    """Instrument Optimizer.step with a RecordEvent while profiling
    (reference ``profiler/utils.py:161``)."""
    from ..optimizer.optimizer import Optimizer

    if getattr(Optimizer, "_profiler_wrapped", False):
        return
    raw_step = Optimizer.step

    def step(self, *args, **kwargs):
        if in_profiler_mode():
            with RecordEvent(f"{type(self).__name__}.step", "Optimization"):
                return raw_step(self, *args, **kwargs)
        return raw_step(self, *args, **kwargs)

    Optimizer.step = step
    Optimizer._profiler_wrapped = True


# ---------------------------------------------------------------------------
# result container + exporters (≙ chrometracing_logger.cc / event_python.cc)
# ---------------------------------------------------------------------------

class ProfilerResult:
    def __init__(self, events, extra_info=None, xplane_dir=None):
        self.events = list(events)
        self.extra_info = dict(extra_info or {})
        self.xplane_dir = xplane_dir

    def save(self, path, format="json"):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if format == "json":
            data = {
                "traceEvents": [
                    {
                        "name": e.name,
                        "cat": e.event_type,
                        "ph": "X",
                        "pid": os.getpid(),
                        "tid": e.tid,
                        "ts": e.start_ns / 1e3,
                        "dur": (e.end_ns - e.start_ns) / 1e3,
                    }
                    for e in self.events
                ],
                "metadata": {"extra_info": self.extra_info,
                             "xplane_dir": self.xplane_dir},
            }
            with open(path, "w") as f:
                json.dump(data, f)
        else:
            raise ValueError(f"unsupported export format: {format}")


def load_profiler_result(filename: str):
    with open(filename) as f:
        data = json.load(f)
    events = [
        _HostEvent(e["name"], e.get("cat", ""), e.get("tid", 0),
                   int(e["ts"] * 1e3), int((e["ts"] + e["dur"]) * 1e3))
        for e in data.get("traceEvents", [])
    ]
    meta = data.get("metadata", {})
    return ProfilerResult(events, meta.get("extra_info"), meta.get("xplane_dir"))


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """on_trace_ready handler factory (reference ``profiler.py:158``)."""
    os.makedirs(dir_name, exist_ok=True)

    def handle_fn(prof):
        nonlocal worker_name
        if not worker_name:
            worker_name = f"host_{socket.gethostname()}pid{os.getpid()}"
        now = time.localtime()
        filename = "{}_time_{}.paddle_trace.json".format(
            worker_name, time.strftime("%Y_%m_%d_%H_%M_%S", now))
        if prof.profiler_result is not None:
            prof.profiler_result.save(os.path.join(dir_name, filename), "json")

    return handle_fn


def export_protobuf(dir_name: str, worker_name: str = None):
    """Reference ``profiler.py:209`` exports its own protobuf; the TPU-native
    device trace is already protobuf XPlane written by jax — this handler just
    reports where it is (host spans keep the chrome-json form)."""
    return export_chrome_tracing(dir_name, worker_name)


# ---------------------------------------------------------------------------
# Profiler
# ---------------------------------------------------------------------------

class Profiler:
    """Reference ``profiler.py:271``. ``targets`` containing GPU/TPU turns on
    the XPlane device capture during RECORD windows; CPU host spans are always
    collected while recording."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU,
                                                      ProfilerTarget.TPU]
        if scheduler is None:
            self.scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self.scheduler = make_scheduler(closed=max(start - 1, 0), ready=1,
                                            record=end - start, repeat=1)
        else:
            self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready or export_chrome_tracing(
            "profiler_log")
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self.profiler_result = None
        self._events = []
        self._device_tracing = False
        self._xplane_dir = None
        self._step_t0 = None
        self._step_times = []

    # -- host event sink --
    def _record(self, ev):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._events.append(ev)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()

    def start(self):
        _ACTIVE_PROFILERS.append(self)
        wrap_optimizers()
        self.current_state = self.scheduler(self.step_num)
        self._maybe_toggle_device()
        self._step_t0 = time.perf_counter()

    def stop(self):
        if self in _ACTIVE_PROFILERS:
            _ACTIVE_PROFILERS.remove(self)
        if self._step_t0 is not None:
            # the in-flight step (started by start()/the last step()) ends
            # here — keep its duration so step_info() reflects the last step
            self._step_times.append(time.perf_counter() - self._step_t0)
            self._step_t0 = None
        self._stop_device()
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._finalize()
            if self.on_trace_ready:
                self.on_trace_ready(self)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        if self._step_t0 is not None:
            self._step_times.append(time.perf_counter() - self._step_t0)
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._stop_device()
            self._finalize()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            self._events = []
        elif (self.current_state not in (ProfilerState.RECORD,
                                         ProfilerState.RECORD_AND_RETURN)
              and _native_state["active"]
              and _native_state["owner"] is self):
            # leaving a record window without returning: keep the spans,
            # stop native collection so non-record phases aren't captured
            _drain_native_tracer(self._events)
        self._maybe_toggle_device()
        self._step_t0 = time.perf_counter()

    def step_info(self, unit=None):
        """Rolling last-10-step timing line; ``unit`` is one of
        ``'s'``/``'ms'``/``'us'`` (default ``'ms'``)."""
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        scale, suffix = {"s": (1.0, "s"), "ms": (1e3, "ms"),
                         "us": (1e6, "us")}.get(unit or "ms", (1e3, "ms"))
        arr = np.asarray(self._step_times[-10:]) * scale
        return (f"step {self.step_num}: avg {arr.mean():.3f} {suffix}, "
                f"max {arr.max():.3f} {suffix}, min {arr.min():.3f} {suffix}")

    # -- device (XPlane) capture --
    def _wants_device(self):
        return any(t in (ProfilerTarget.GPU, ProfilerTarget.TPU)
                   for t in self.targets)

    def _maybe_toggle_device(self):
        recording = self.current_state in (ProfilerState.RECORD,
                                           ProfilerState.RECORD_AND_RETURN)
        if (recording and not _native_state["active"]
                and len(_ACTIVE_PROFILERS) == 1
                and ProfilerTarget.CPU in self.targets):
            _start_native_tracer(self)
        if recording and self._wants_device() and not self._device_tracing:
            import tempfile

            self._xplane_dir = tempfile.mkdtemp(prefix="paddle_tpu_xplane_")
            try:
                import jax.profiler as jp

                jp.start_trace(self._xplane_dir)
                self._device_tracing = True
            except Exception:
                self._xplane_dir = None

    def _stop_device(self):
        if self._device_tracing:
            try:
                import jax.profiler as jp

                jp.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def _finalize(self):
        if _native_state["owner"] is self:
            _drain_native_tracer(self._events)
        events = list(self._events)
        # pipeline telemetry spans (data_wait/h2d_copy/compile/dispatch/
        # readback, same perf_counter_ns clock) merge into the chrome trace
        from . import telemetry as _telemetry

        for name, s_ns, e_ns, tid in _telemetry.get_telemetry().chrome_spans():
            events.append(_HostEvent(f"telemetry::{name}", "Telemetry",
                                     tid, s_ns, e_ns))
        self.profiler_result = ProfilerResult(
            events,
            extra_info={"steps": self.step_num},
            xplane_dir=self._xplane_dir,
        )

    def export(self, path="", format="json"):
        if self.profiler_result is not None:
            self.profiler_result.save(path, format)

    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail=True,
                thread_sep=False, time_unit="ms"):
        """Aggregated host-span table (reference ``profiler.py:715`` →
        ``profiler_statistic._build_table``)."""
        events = (self.profiler_result.events
                  if self.profiler_result is not None else self._events)
        agg = {}
        for e in events:
            d = agg.setdefault(e.name, [0, 0.0, float("inf"), 0.0])
            dur = (e.end_ns - e.start_ns) / 1e6
            d[0] += 1
            d[1] += dur
            d[2] = min(d[2], dur)
            d[3] = max(d[3], dur)
        key_fn = {SortedKeys.CPUTotal: lambda d: d[1],
                  SortedKeys.CPUAvg: lambda d: d[1] / d[0],
                  SortedKeys.CPUMax: lambda d: d[3],
                  SortedKeys.CPUMin: lambda d: d[2],
                  SortedKeys.GPUTotal: lambda d: d[1],
                  SortedKeys.GPUAvg: lambda d: d[1] / d[0],
                  SortedKeys.GPUMax: lambda d: d[3],
                  SortedKeys.GPUMin: lambda d: d[2]}.get(
                      sorted_by, lambda d: d[1])
        rows = sorted(agg.items(), key=lambda kv: -key_fn(kv[1]))
        lines = [f"{'Name':<40} {'Calls':>6} {'Total(ms)':>12} "
                 f"{'Avg(ms)':>10} {'Min(ms)':>10} {'Max(ms)':>10}"]
        lines.append("-" * 92)
        for name, (cnt, tot, mn, mx) in rows:
            lines.append(f"{name[:40]:<40} {cnt:>6} {tot:>12.3f} "
                         f"{tot / cnt:>10.3f} {mn:>10.3f} {mx:>10.3f}")
        table = "\n".join(lines)
        print(table)
        return table


def get_profiler(config_path=None):
    return Profiler()
