"""OpenMetrics exposition over the telemetry registry.

A fleet monitor (Prometheus-compatible) can't consume ``telemetry.report()``
tables; this renders the registry in the OpenMetrics text format and serves
it from a stdlib ``http.server`` endpoint:

* counters → ``counter`` families (``serve.decode_steps`` →
  ``serve_decode_steps_total``);
* gauges → ``gauge`` families (``step.time_s`` → ``step_time_s``);
* histograms (``observe()``/phase timings) → ``summary`` families carrying
  the *exact* running ``_count``/``_sum`` (so scraped rates are correct)
  alongside ``quantile="0.5"``/``"0.95"`` samples from the bounded
  reservoirs (see ``Telemetry.histogram_stats``).

The endpoint is opt-in (``telemetry.serve_metrics(port=...)`` /
:func:`serve_metrics`) and renders on demand inside the GET handler — the
serving/training hot paths never see it, preserving the
zero-overhead-when-disabled telemetry contract. ``tools/metrics_scrape.py``
is the stdlib round-trip scraper/parser used by the CI smoke.
"""
from __future__ import annotations

import re
import threading

__all__ = [
    "openmetrics_name",
    "render_openmetrics",
    "MetricsServer",
    "serve_metrics",
    "CONTENT_TYPE",
]

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: reservoir quantiles exposed on summary families
QUANTILES = (0.5, 0.95)


def openmetrics_name(name):
    """Registry key → OpenMetrics metric name (``serve.ttft_s`` →
    ``serve_ttft_s``). Metric names must match
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = _NAME_RE.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v):
    """Sample value formatting: integers bare, floats via repr (full
    precision — the round-trip parser must reproduce exact counts/sums)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc_help(text):
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def render_openmetrics(telemetry=None):
    """The registry as OpenMetrics text (terminated by ``# EOF``). Pass a
    :class:`~paddle_tpu.profiler.telemetry.Telemetry` or default to the
    process-wide one. Works whether or not collection is currently
    enabled — it renders whatever the registry holds."""
    if telemetry is None:
        from . import telemetry as _telemetry

        telemetry = _telemetry.get_telemetry()
    counters = telemetry.counters()
    gauges = telemetry.gauges()
    hists = telemetry.histogram_stats(include_phases=True)

    lines = []
    used = set()

    def _family(raw, kind):
        fam = openmetrics_name(raw)
        if kind == "counter" and fam.endswith("_total"):
            fam = fam[: -len("_total")]
        # two registry keys may sanitize to one name; suffix to keep
        # families unique rather than emitting an invalid exposition
        base, n = fam, 2
        while fam in used:
            fam = f"{base}_{n}"
            n += 1
        used.add(fam)
        lines.append(f"# TYPE {fam} {kind}")
        lines.append(f"# HELP {fam} "
                     f"{_esc_help(f'paddle_tpu telemetry {kind} {raw!r}')}")
        return fam

    for raw in sorted(counters):
        fam = _family(raw, "counter")
        lines.append(f"{fam}_total {_fmt(counters[raw])}")
    for raw in sorted(gauges):
        fam = _family(raw, "gauge")
        lines.append(f"{fam} {_fmt(gauges[raw])}")
    for raw in sorted(hists):
        st = hists[raw]
        fam = _family(raw, "summary")
        for q in QUANTILES:
            key = f"p{int(q * 100)}"
            if key in st:
                lines.append(f'{fam}{{quantile="{q}"}} {_fmt(st[key])}')
        lines.append(f"{fam}_count {_fmt(st.get('count', 0))}")
        lines.append(f"{fam}_sum {_fmt(st.get('sum', 0.0))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Background ``/metrics`` endpoint over the telemetry registry.

    ``MetricsServer(port=0)`` binds an ephemeral port (read it back from
    ``.port``), serves GETs on ``/metrics`` (and ``/``) from a daemon
    thread, and tears down on :meth:`close` (context-manager supported).
    Rendering happens inside the request handler; an idle endpoint costs
    nothing on the instrumented paths.
    """

    def __init__(self, port=0, addr="127.0.0.1", telemetry=None):
        import http.server

        registry = telemetry

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404, "try /metrics")
                    return
                try:
                    body = render_openmetrics(registry).encode("utf-8")
                except Exception as e:  # pragma: no cover - render bug guard
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._httpd = http.server.ThreadingHTTPServer((addr, int(port)),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.addr = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="pt-metrics",
            daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://{self.addr}:{self.port}/metrics"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def serve_metrics(port=0, addr="127.0.0.1", telemetry=None):
    """Start the ``/metrics`` endpoint; returns the :class:`MetricsServer`
    (``.url`` for the scrape target, ``.close()`` to stop). Also exposed as
    ``profiler.telemetry.serve_metrics`` for discoverability."""
    return MetricsServer(port=port, addr=addr, telemetry=telemetry)
