"""Higher-order autodiff (reference ``incubate/autograd/``:
``primapi.py:22 forward_grad``, ``functional.py:172 Jacobian``, ``:262
Hessian`` over the ``prim_ops`` primitive layer).

TPU-native: jax already exposes composable forward/reverse transforms, so
these are direct lowerings — no primitive-op rewrite layer needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["grad", "forward_grad", "jvp", "vjp", "Jacobian", "Hessian"]


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _pure(func, n_inputs):
    def f(*arrays):
        out = func(*[Tensor(a) for a in arrays])
        return out._value if isinstance(out, Tensor) else out

    return f


def grad(func, xs, create_graph=False):
    """Gradient of a scalar-valued ``func`` with support for higher-order
    composition (``create_graph`` is implicit: the returned Tensors are
    produced by ops, so they can be differentiated again)."""
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    from ..ops.dispatch import apply_op

    f = _pure(func, len(xs))

    def fwd(*arrays):
        gs = jax.grad(f, argnums=tuple(range(len(arrays))))(*arrays)
        return tuple(gs)

    out = apply_op("incubate_grad", fwd, tuple(xs), {})
    return out if len(xs) > 1 else out[0]


def jvp(func, xs, v):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    v = v if isinstance(v, (list, tuple)) else [v]
    f = _pure(func, len(xs))
    y, tangent = jax.jvp(f, tuple(_unwrap(x) for x in xs),
                         tuple(_unwrap(t) for t in v))
    return Tensor(y), Tensor(tangent)


forward_grad = jvp


def vjp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    f = _pure(func, len(xs))
    y, pullback = jax.vjp(f, *[_unwrap(x) for x in xs])
    if v is None:
        v = jnp.ones_like(y)
    else:
        v = _unwrap(v)
    gs = pullback(v)
    gs = [Tensor(g) for g in gs]
    return Tensor(y), (gs if len(gs) > 1 else gs[0])


class Jacobian:
    """reference functional.py:172 — lazy full Jacobian."""

    def __init__(self, func, xs, is_batched=False):
        xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
        f = _pure(func, len(xs_list))
        jac = jax.jacobian(f, argnums=tuple(range(len(xs_list))))(
            *[_unwrap(x) for x in xs_list]
        )
        self._jac = jac if len(xs_list) > 1 else (jac[0],)
        self._single = len(xs_list) == 1

    def __getitem__(self, idx):
        return Tensor(self._jac[0][idx]) if self._single else Tensor(self._jac[idx[0]][idx[1:]])

    @property
    def shape(self):
        return list(self._jac[0].shape)

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac[0])


class Hessian(Jacobian):
    """reference functional.py:262 — Hessian of a scalar func."""

    def __init__(self, func, xs, is_batched=False):
        xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
        f = _pure(func, len(xs_list))

        def scalar(*arrays):
            out = f(*arrays)
            return out.reshape(())

        h = jax.hessian(scalar, argnums=0)(*[_unwrap(x) for x in xs_list])
        self._jac = (h,)
        self._single = True
