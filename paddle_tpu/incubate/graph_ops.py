"""Segment reductions + graph message passing + fused softmax masks
(reference ``python/paddle/incubate/tensor/math.py`` segment ops,
``incubate/operators/graph_send_recv.py`` and friends,
``incubate/operators/softmax_mask_fuse*.py``).

TPU-native: segment reductions ARE ``jax.ops.segment_*`` (sorted or not);
graph sampling runs host-side on numpy (it is data preparation, like the
reference's CPU kernels)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_nondiff_op, apply_op

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "graph_send_recv", "graph_reindex", "graph_sample_neighbors",
    "graph_khop_sampler", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "identity_loss",
]


def _num_segments(segment_ids):
    return int(np.asarray(
        segment_ids._value if isinstance(segment_ids, Tensor)
        else segment_ids).max()) + 1


def _segment(kind, data, segment_ids, n):
    fns = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
           "max": jax.ops.segment_max}

    def fwd(d, ids):
        if kind == "mean":
            s = jax.ops.segment_sum(d, ids, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(ids, d.dtype), ids,
                                    num_segments=n)
            return s / jnp.maximum(c, 1).reshape((-1,) + (1,) * (d.ndim - 1))
        return fns[kind](d, ids, num_segments=n)

    return apply_op(f"segment_{kind}", fwd, (data, segment_ids), {})


def segment_sum(data, segment_ids, name=None):
    return _segment("sum", data, segment_ids, _num_segments(segment_ids))


def segment_mean(data, segment_ids, name=None):
    return _segment("mean", data, segment_ids, _num_segments(segment_ids))


def segment_min(data, segment_ids, name=None):
    return _segment("min", data, segment_ids, _num_segments(segment_ids))


def segment_max(data, segment_ids, name=None):
    return _segment("max", data, segment_ids, _num_segments(segment_ids))


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Gather features at ``src_index``, reduce onto ``dst_index``
    (reference ``graph_send_recv.py:22``)."""
    n = int(out_size) if out_size is not None else x.shape[0]
    kind = pool_type.lower()

    def fwd(xv, si, di):
        msgs = xv[si]
        if kind == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(di, xv.dtype), di,
                                    num_segments=n)
            return s / jnp.maximum(c, 1).reshape(
                (-1,) + (1,) * (xv.ndim - 1))
        fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
              "max": jax.ops.segment_max}[kind]
        out = fn(msgs, di, num_segments=n)
        if kind in ("min", "max"):
            # empty segments: reference emits 0, segment_min/max emit +-inf
            c = jax.ops.segment_sum(jnp.ones_like(di, jnp.int32), di,
                                    num_segments=n)
            out = jnp.where((c > 0).reshape(
                (-1,) + (1,) * (xv.ndim - 1)), out, 0)
        return out

    return apply_op("graph_send_recv", fwd, (x, src_index, dst_index), {})


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a sampled subgraph to contiguous local ids (reference
    ``incubate/operators/graph_reindex.py``): returns (reindexed_src,
    reindexed_dst, out_nodes) where out_nodes = unique center+neighbor
    nodes in first-seen order."""
    xs = np.asarray(x._value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._value if isinstance(neighbors, Tensor)
                    else neighbors)
    ct = np.asarray(count._value if isinstance(count, Tensor) else count)
    order = {}
    for v in list(xs) + list(nb):
        v = int(v)
        if v not in order:
            order[v] = len(order)
    out_nodes = np.fromiter(order.keys(), dtype=xs.dtype)
    src = np.array([order[int(v)] for v in nb], dtype=np.int64)
    # dst ids come from the order[] map, not arange: duplicate centers in x
    # collapse into one first-seen slot, so positional ids would drift.
    dst = np.repeat(np.array([order[int(v)] for v in xs], dtype=np.int64), ct)
    return Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)), \
        Tensor(jnp.asarray(out_nodes))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Sample up to ``sample_size`` in-neighbors per input node from a CSC
    graph (reference ``incubate/operators/graph_sample_neighbors.py``).
    Host-side numpy (data preparation, like the reference CPU kernel)."""
    from ..framework import random as rnd

    r = np.asarray(row._value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    seed = int(np.asarray(
        jax.random.randint(rnd.next_key(), (), 0, 2**31 - 1)))
    g = np.random.RandomState(seed)
    out, counts, out_eids = [], [], []
    ev = (np.asarray(eids._value if isinstance(eids, Tensor) else eids)
          if eids is not None else None)
    for nid in nodes:
        lo, hi = int(cp[nid]), int(cp[nid + 1])
        idx = np.arange(lo, hi)
        if sample_size >= 0 and len(idx) > sample_size:
            idx = g.choice(idx, size=sample_size, replace=False)
        out.extend(r[idx].tolist())
        counts.append(len(idx))
        if ev is not None:
            out_eids.extend(ev[idx].tolist())
    neigh = Tensor(jnp.asarray(np.array(out, r.dtype)))
    cnt = Tensor(jnp.asarray(np.array(counts, np.int32)))
    if return_eids:
        return neigh, cnt, Tensor(jnp.asarray(np.array(out_eids)))
    return neigh, cnt


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling + reindex (reference
    ``incubate/operators/graph_khop_sampler.py``)."""
    cur = input_nodes
    all_neigh, all_cnt = [], []
    frontier = cur
    for size in sample_sizes:
        neigh, cnt = graph_sample_neighbors(row, colptr, frontier,
                                            sample_size=size)
        all_neigh.append(neigh)
        all_cnt.append(cnt)
        frontier = neigh
    neighbors = Tensor(jnp.concatenate([n._value for n in all_neigh]))
    counts = Tensor(jnp.concatenate([c._value for c in all_cnt]))
    # centers for reindex: the concatenated frontiers aligned with counts
    centers = Tensor(jnp.concatenate(
        [jnp.asarray(np.asarray(c._value if isinstance(c, Tensor) else c))
         for c in ([input_nodes] + all_neigh[:-1])]))
    src, dst, nodes = graph_reindex(centers, neighbors, counts)
    return src, dst, nodes, counts


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one pass (reference
    ``incubate/operators/softmax_mask_fuse.py`` — the CUDA kernel fuses;
    XLA fuses this composition on TPU by construction)."""

    def fwd(xv, mv):
        return jax.nn.softmax(xv + mv, axis=-1)

    return apply_op("softmax_mask_fuse", fwd, (x, mask), {})


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper triangle masked out (causal; reference
    ``softmax_mask_fuse_upper_triangle.py``)."""

    def fwd(xv):
        q, k = xv.shape[-2], xv.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (q, k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (q, k), 1)
        masked = jnp.where(cols <= rows, xv, -1e30)
        return jax.nn.softmax(masked, axis=-1)

    return apply_op("softmax_mask_fuse_upper_triangle", fwd, (x,), {})


def identity_loss(x, reduction="none"):
    """reference ``incubate/identity_loss``: mark a value as the loss
    (IPU-era marker); reduces per ``reduction``.

    Integer codes follow the reference contract (``fluid/layers/loss.py``
    identity_loss): 0 = 'sum', 1 = 'mean', 2 = 'none'."""
    if reduction in ("none", 2):
        return x
    if reduction in ("sum", 0):
        return x.sum()
    if reduction in ("mean", 1):
        return x.mean()
    raise ValueError(
        f"identity_loss reduction must be 'sum'/0, 'mean'/1 or 'none'/2, "
        f"got {reduction!r}")
