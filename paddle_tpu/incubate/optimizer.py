"""incubate optimizers (reference ``python/paddle/incubate/optimizer/``:
``lookahead.py LookAhead``, ``modelaverage.py ModelAverage``)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage", "DistributedFusedLamb"]


class LookAhead(Optimizer):
    """Reference ``lookahead.py``: k fast steps with the inner optimizer,
    then slow weights move ``alpha`` toward the fast weights and the fast
    weights reset to slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("inner_optimizer must be an Optimizer")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        # slow weights seeded from the INITIAL parameters (reference
        # lookahead.py seeds the accumulator with the param value at
        # creation, before any fast step). Keyed by param name so the state
        # forms a stable pytree for jit threading.
        # copy: slow weights must be distinct buffers from the params (a
        # shared buffer would be donated twice under a donating jit step)
        self._slow = {self._pname(p): jnp.array(p._value, copy=True)
                      for p in (inner_optimizer._parameter_list or [])}
        self._k_count = jnp.zeros((), jnp.int32)
        self._parameter_list = inner_optimizer._parameter_list

    @staticmethod
    def _pname(p):
        return Optimizer._pkey(p)

    def step(self):
        """jit-compatible: the every-k sync is a traced ``where`` blend, and
        the counter/slow weights are threaded state (see _state_pytree)."""
        self.inner_optimizer.step()
        self._k_count = self._k_count + 1
        sync = (self._k_count % self.k) == 0
        for p in self.inner_optimizer._parameter_list or []:
            key = self._pname(p)
            slow = self._slow[key].astype(jnp.float32)
            fast = p._value.astype(jnp.float32)
            slow_new = jnp.where(sync, slow + self.alpha * (fast - slow), slow)
            self._slow[key] = slow_new
            p._value = jnp.where(sync, slow_new, fast).astype(p._value.dtype)

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero=set_to_zero)

    # -- state threading (CompiledStep) / checkpointing ----------------------
    def _state_pytree(self):
        return {
            "inner": self.inner_optimizer._state_pytree(),
            "slow": dict(self._slow),
            "k_count": self._k_count,
        }

    def _load_state_pytree(self, tree):
        self.inner_optimizer._load_state_pytree(tree["inner"])
        self._slow = dict(tree["slow"])
        self._k_count = tree["k_count"]

    def state_dict(self):
        import numpy as np

        sd = self.inner_optimizer.state_dict()
        sd["@lookahead_k_count"] = int(np.asarray(self._k_count))
        for key, v in self._slow.items():
            sd[f"@lookahead_slow_{key}"] = v
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._k_count = jnp.asarray(int(sd.pop("@lookahead_k_count", 0)),
                                    jnp.int32)
        for key in list(self._slow):
            v = sd.pop(f"@lookahead_slow_{key}", None)
            if v is not None:
                self._slow[key] = jnp.asarray(
                    v._value if isinstance(v, Tensor) else v)
        self.inner_optimizer.set_state_dict(sd)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def __getattr__(self, name):
        return getattr(self.__dict__["inner_optimizer"], name)


class ModelAverage:
    """Reference ``modelaverage.py``: exponential/windowed average of
    parameter trajectories; ``apply()`` swaps averaged weights in (context
    manager), ``restore()`` swaps back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._sum = {id(p): jnp.zeros_like(p._value) for p in self._params}
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current weights (call after optimizer.step())."""
        self._count += 1
        if self._count > self.max_window:
            # restart the window (reference restart semantics)
            for p in self._params:
                self._sum[id(p)] = p._value.astype(jnp.float32)
            self._count = 1
            return
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._value.astype(jnp.float32)

    def apply(self, executor=None, need_restore=True):
        """Swap in averaged weights. Usable as a context manager."""
        if self._count == 0:
            raise RuntimeError("ModelAverage.apply before any step()")
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            p._value = (self._sum[id(p)] / self._count).astype(p._value.dtype)
        mgr = self

        class _Ctx:
            def __enter__(self_c):
                return mgr

            def __exit__(self_c, *exc):
                if need_restore:
                    mgr.restore()
                return False

        return _Ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._value = self._backup[id(p)]
        self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()


class DistributedFusedLamb(Optimizer):
    """Reference ``incubate/optimizer/distributed_fused_lamb.py`` (CUDA op
    ``distributed_fused_lamb_op``): LAMB with gradient allreduce, global
    grad-norm clipping, and fused multi-tensor updates for large-batch
    multi-device training.

    TPU-native redesign: "fused multi-tensor" is XLA's job (the whole step
    compiles into one program) and the gradient allreduce is a mesh psum —
    what remains semantically is LAMB with (a) optional global-norm clip
    BEFORE the trust-ratio update and (b) grads averaged over the data
    group when one is active.
    """

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, group=None, exclude_from_weight_decay_fn=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn
        self._group = group
        self._clip_after_allreduce = clip_after_allreduce
        self._scaled_by_nranks = is_grad_scaled_by_nranks

    def _sync_grads(self):
        import jax

        from ..distributed import collective
        from ..framework.tensor import Tensor

        # single-controller runs hold GLOBAL grads already (XLA psums them
        # inside the step); an eager all_reduce there would re-shard dim 0.
        # Sync only in the real multi-controller case, where each process
        # holds its local grad (the _mp_eager path in collective.py).
        if jax.process_count() <= 1:
            return
        group = self._group
        n = group.nranks if group is not None else jax.process_count()
        if n <= 1:
            return
        for p in self._parameter_list or []:
            if p.stop_gradient or p.grad is None:
                continue
            synced = collective.all_reduce(Tensor(p.grad._value), group=group)
            g = synced._value / n if self._scaled_by_nranks else synced._value
            p._grad = Tensor(g)

    def step(self):
        self._sync_grads()
        super().step()

    def _update_param(self, p, grad, lr):
        m = self._add_accumulator("moment1", p)
        v = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow", p, fill_value=1.0, shape=())
        b2p = self._add_accumulator("beta2_pow", p, fill_value=1.0, shape=())
        b1p = b1p * self._beta1
        b2p = b2p * self._beta2
        m_new = self._beta1 * m + (1 - self._beta1) * grad
        v_new = self._beta2 * v + (1 - self._beta2) * jnp.square(grad)
        self._set_accumulator("moment1", p, m_new)
        self._set_accumulator("moment2", p, v_new)
        self._set_accumulator("beta1_pow", p, b1p)
        self._set_accumulator("beta2_pow", p, b2p)
        m_hat = m_new / (1 - b1p)
        v_hat = v_new / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None
                     and self._exclude_fn(p)) else self._wd
        update = r + wd * p._value.astype(r.dtype)
        w_norm = jnp.linalg.norm(p._value.astype(jnp.float32))
        u_norm = jnp.linalg.norm(update.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return p._value - lr * trust * update
