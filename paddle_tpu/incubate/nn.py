"""incubate.nn — fused transformer building blocks.

Reference: ``python/paddle/incubate/nn/layer/fused_transformer.py``
(FusedMultiHeadAttention:176, FusedFeedForward:437,
FusedTransformerEncoderLayer:641, FusedBiasDropoutResidualLayerNorm:79)
backed by the monolithic CUDA kernels ``fused_attention_op.cu`` /
``fused_feedforward_op.cu``.

TPU-native: the same layer surface, but "fused" means ONE traced region —
the flash-attention Pallas kernel (or XLA's fused einsum at short seq) plus
XLA elementwise fusion cover what the hand-written CUDA kernels do; there
is no separate semantics to keep, so these layers express the reference's
pre/post-layernorm + residual-dropout orchestration exactly.
"""
from __future__ import annotations

import math

from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..nn.layer.common import Dropout, Linear

__all__ = [
    "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention",
    "FusedFeedForward",
    "FusedTransformerEncoderLayer",
]


class FusedBiasDropoutResidualLayerNorm(Layer):
    """Reference ``fused_transformer.py:79``: out = LN(residual +
    dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.dropout = Dropout(dropout_rate, mode="upscale_in_train")
        self.norm = LayerNorm(embed_dim, epsilon=epsilon,
                              weight_attr=weight_attr)

    def forward(self, x, residual):
        return self.norm(residual + self.dropout(x + self.linear_bias))


class FusedMultiHeadAttention(Layer):
    """Reference ``fused_transformer.py:176``: qkv proj + sdpa + out proj
    with pre/post layernorm and residual dropout in one fused region."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim,
                               weight_attr=qkv_weight_attr,
                               bias_attr=qkv_bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=linear_weight_attr,
                               bias_attr=linear_bias_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon,
                                weight_attr=pre_ln_scale_attr,
                                bias_attr=pre_ln_bias_attr)
        self.ln = LayerNorm(embed_dim, epsilon=epsilon,
                            weight_attr=ln_scale_attr, bias_attr=ln_bias_attr)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = Dropout(dropout_rate, mode="upscale_in_train")

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            is_causal=False, training=self.training)
        out = self.out_proj(attn.reshape([b, s, h]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    """Reference ``fused_transformer.py:437``: LN + linear/act/dropout/
    linear + residual in one fused region."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr,
                              bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr,
                              bias_attr=linear2_bias_attr)
        self.ln1 = LayerNorm(d_model, epsilon=epsilon,
                             weight_attr=ln1_scale_attr, bias_attr=ln1_bias_attr)
        self.ln2 = LayerNorm(d_model, epsilon=epsilon,
                             weight_attr=ln2_scale_attr, bias_attr=ln2_bias_attr)
        self.dropout = Dropout(dropout_rate, mode="upscale_in_train")
        self.act_dropout = Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate,
            mode="upscale_in_train")

    def forward(self, src, cache=None):
        residual = src
        x = self.ln1(src) if self.normalize_before else src
        act = getattr(F, self.activation)
        x = self.linear2(self.act_dropout(act(self.linear1(x))))
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.ln2(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    """Reference ``fused_transformer.py:641``: fused attention + fused FFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        ad = dropout_rate if attn_dropout_rate is None else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate, attn_dropout_rate=ad,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
