"""MoE gates (reference ``incubate/distributed/models/moe/gate/``:
``naive_gate.py``, ``gshard_gate.py``, ``switch_gate.py``).

Each gate maps token features -> (combine_weights, dispatch_mask, aux_loss)
in the GShard dense-dispatch form:

  combine_weights: [tokens, experts, capacity] float — weight for gathering
  dispatch_mask:   [tokens, experts, capacity] bool  — token→slot routing
  aux_loss:        scalar load-balance loss (0 for the naive gate)

The cumsum position-assignment is branch-free and jit-friendly; tokens past
an expert's capacity are dropped exactly like the reference's ``prune_gate``
path (their combine weight is zero, so the residual passes through).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....nn.layer.common import Linear

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "BaseGate"]


def _positions(mask, offset=None):
    """Slot index of each kept token within its expert (cumsum-1), plus an
    optional per-expert base offset [experts]."""
    pos = jnp.cumsum(mask, axis=0) - 1
    if offset is not None:
        pos = pos + offset[None, :]
    return pos


def _dispatch_onehot(mask, pos, capacity):
    """[S, E] keep-mask + [S, E] positions -> [S, E, C] slot one-hot."""
    keep = (mask > 0) & (pos < capacity)
    slot = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity + 1,
                          dtype=jnp.float32)[..., :capacity]
    return slot * keep[..., None].astype(jnp.float32)


def _load_balance_loss(probs, top1_mask, num_experts):
    """GShard/Switch auxiliary loss: E * sum_e mean(probs_e) * mean(mask_e)."""
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(top1_mask.astype(jnp.float32), axis=0)
    return num_experts * jnp.sum(me * ce)


class BaseGate(Layer):
    def __init__(self, d_model, num_experts, capacity_factor=1.2):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.linear = Linear(d_model, num_experts, bias_attr=False)

    def capacity(self, num_tokens, k=1):
        return max(1, int(self.capacity_factor * k * num_tokens / self.num_experts))

    def logits(self, x):
        return self.linear(x)


class NaiveGate(BaseGate):
    """reference naive_gate.py: plain top-k softmax routing, no aux loss."""

    top_k = 1

    def dispatch_fn(self, logits_v, capacity):
        probs = jax.nn.softmax(logits_v, axis=-1)
        top1 = jnp.argmax(probs, axis=-1)
        mask = jax.nn.one_hot(top1, self.num_experts, dtype=jnp.float32)
        pos = _positions(mask)
        slot = _dispatch_onehot(mask, pos, capacity)
        gate = jnp.sum(probs * mask, axis=-1)
        combine = slot * gate[:, None, None]
        return combine, slot > 0, jnp.zeros((), jnp.float32)


class SwitchGate(BaseGate):
    """reference switch_gate.py: top-1 routing + load-balance aux loss."""

    top_k = 1

    def dispatch_fn(self, logits_v, capacity):
        probs = jax.nn.softmax(logits_v, axis=-1)
        top1 = jnp.argmax(probs, axis=-1)
        mask = jax.nn.one_hot(top1, self.num_experts, dtype=jnp.float32)
        aux = _load_balance_loss(probs, mask, self.num_experts)
        pos = _positions(mask)
        slot = _dispatch_onehot(mask, pos, capacity)
        gate = jnp.sum(probs * mask, axis=-1)
        combine = slot * gate[:, None, None]
        return combine, slot > 0, aux


class GShardGate(BaseGate):
    """reference gshard_gate.py: top-2 routing, normalized gates, aux loss on
    the top-1 assignment."""

    top_k = 2

    def dispatch_fn(self, logits_v, capacity):
        probs = jax.nn.softmax(logits_v, axis=-1)
        e = self.num_experts
        top1 = jnp.argmax(probs, axis=-1)
        mask1 = jax.nn.one_hot(top1, e, dtype=jnp.float32)
        probs2 = probs * (1.0 - mask1)
        top2 = jnp.argmax(probs2, axis=-1)
        mask2 = jax.nn.one_hot(top2, e, dtype=jnp.float32)

        aux = _load_balance_loss(probs, mask1, e)

        pos1 = _positions(mask1)
        # expert slots already taken by first choices
        used1 = jnp.sum(mask1, axis=0)
        pos2 = _positions(mask2, offset=used1)
        slot1 = _dispatch_onehot(mask1, pos1, capacity)
        slot2 = _dispatch_onehot(mask2, pos2, capacity)

        g1 = jnp.sum(probs * mask1, axis=-1)
        g2 = jnp.sum(probs * mask2, axis=-1)
        denom = jnp.maximum(g1 + g2, 1e-9)
        g1, g2 = g1 / denom, g2 / denom

        combine = slot1 * g1[:, None, None] + slot2 * g2[:, None, None]
        dispatch = (slot1 + slot2) > 0
        return combine, dispatch, aux
