"""Mixture-of-experts layer with expert parallelism.

Reference: ``incubate/distributed/models/moe/moe_layer.py:244 MoELayer`` —
token dispatch via ``global_scatter``/``global_gather`` all-to-all CUDA ops
(``operators/collective/global_scatter_op.cc``), experts bound per rank.

TPU-native redesign (GShard dense dispatch): expert parameters are STACKED
``[E, ...]`` and sharded over the MoE group's mesh axis; routing is a pair
of einsums against the gate's dispatch/combine one-hots

    dispatched = einsum('sec,sm->ecm', dispatch, tokens)
    out        = einsum('sec,ecm->sm', combine,  expert_out)

whose resharding (tokens: data-sharded -> expert-sharded and back) XLA's
SPMD partitioner lowers to exactly the all_to_all pair the reference codes
by hand — fused with the surrounding matmuls.  The expert computation runs
as ``jax.vmap`` of a functional apply over the stacked weights, so experts
can be arbitrary (identical-structure) Layers.
"""
from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....framework.tensor import Parameter, Tensor
from .....nn.layer.layers import Layer
from .....ops.dispatch import apply_op
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


@contextmanager
def _install(tensors, values):
    old = [t._value for t in tensors]
    for t, v in zip(tensors, values):
        t._value = v
    try:
        yield
    finally:
        for t, o in zip(tensors, old):
            t._value = o


def _make_gate(gate, d_model, num_experts):
    if isinstance(gate, BaseGate):
        return gate
    cfg = dict(gate or {})
    typ = cfg.pop("type", "gshard")
    cls = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}[typ]
    top_k = cfg.pop("top_k", None)
    g = cls(d_model, num_experts, **cfg)
    if top_k is not None:
        g.top_k = int(top_k)
    return g


class MoELayer(Layer):
    """``MoELayer(d_model, experts, gate={'type': 'gshard'}, moe_group=...)``

    ``experts``: list of identical-structure Layers (one per expert).
    ``moe_group``: collective Group whose mesh axis carries the experts
    (defaults to the fleet data-parallel group when initialized; dense
    single-device execution otherwise).  After ``forward`` the gate's
    auxiliary load-balance loss is available as ``self.aux_loss`` (a Tensor
    on the autograd graph — add it to the training loss, reference
    ``gate.get_loss()``).
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, name=None,
                 capacity_factor=None):
        super().__init__()
        experts = list(experts)
        self.num_experts = len(experts)
        self.d_model = d_model
        self.gate = _make_gate(gate, d_model, self.num_experts)
        if capacity_factor is not None:
            self.gate.capacity_factor = float(capacity_factor)
        self.moe_group = moe_group if moe_group is not None else self._default_group()
        # expert-parallel sharding needs the expert count to tile the group
        # axis; otherwise run dense/replicated (the reference requires
        # num_experts % world_size == 0 — here it degrades gracefully)
        if (self.moe_group is not None
                and self.num_experts % self.moe_group.nranks != 0):
            self.moe_group = None
        self.aux_loss = None

        # stack expert params (template apply pattern, like the pipeline)
        object.__setattr__(self, "_template", experts[0])
        tmpl_named = list(experts[0].named_parameters())
        self._tmpl_params = [p for _, p in tmpl_named]
        self._stacked = []
        mesh_axis = None
        if self.moe_group is not None:
            mesh_axis = (self.moe_group.mesh, self.moe_group.axis_name)
        for name_, p0 in tmpl_named:
            per = []
            for ex in experts:
                q = dict(ex.named_parameters())[name_]
                if tuple(q.shape) != tuple(p0.shape):
                    raise ValueError(
                        f"expert param {name_} shape mismatch: {q.shape} vs {p0.shape}"
                    )
                per.append(q._value)
            arr = jnp.stack(per)
            if mesh_axis is not None:
                mesh, axis = mesh_axis
                arr = jax.device_put(arr, NamedSharding(mesh, P(axis)))
            sp = Parameter(arr, trainable=not p0.stop_gradient)
            sp.optimize_attr = dict(p0.optimize_attr)
            self.add_parameter("experts__" + name_.replace(".", "__"), sp)
            self._stacked.append(sp)

    @staticmethod
    def _default_group():
        from .....distributed.fleet.base.fleet_base import (
            get_hybrid_communicate_group,
        )

        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            return hcg.get_data_parallel_group()
        return None

    def forward(self, x):
        orig_shape = list(x.shape)
        d = orig_shape[-1]
        # static python math: shapes are ints; jnp here would break jit tracing
        tokens = 1
        for s in orig_shape[:-1]:
            tokens *= int(s)
        x2 = x.reshape([-1, d])
        capacity = self.gate.capacity(tokens, k=self.gate.top_k)

        gate_params = list(self.gate.parameters())
        n_gate = len(gate_params)
        n_stack = len(self._stacked)
        template, tmpl_params = self._template, self._tmpl_params
        gate_obj = self.gate
        axis = self.moe_group.axis_name if self.moe_group is not None else None
        mesh = self.moe_group.mesh if self.moe_group is not None else None

        def fwd(*arrays):
            gvals = arrays[:n_gate]
            svals = list(arrays[n_gate:n_gate + n_stack])
            xv = arrays[-1]

            from .....autograd import no_grad

            with _install(gate_params, gvals), no_grad():
                logits = gate_obj.logits(Tensor(xv))._value
            combine, dispatch, aux = gate_obj.dispatch_fn(
                logits.astype(jnp.float32), capacity
            )

            dispatched = jnp.einsum(
                "sec,sm->ecm", dispatch.astype(xv.dtype), xv
            )
            if mesh is not None:
                dispatched = jax.lax.with_sharding_constraint(
                    dispatched,
                    NamedSharding(mesh, P(axis)),
                )

            def one_expert(leaves, toks):
                with _install(tmpl_params, leaves), no_grad():
                    return template(Tensor(toks))._value

            expert_out = jax.vmap(one_expert)(svals, dispatched)
            out = jnp.einsum(
                "sec,ecm->sm", combine.astype(expert_out.dtype), expert_out
            )
            return out, aux

        args = gate_params + self._stacked + [x2]
        out, aux = apply_op("moe_layer", fwd, tuple(args), {})
        self.aux_loss = aux
        return out.reshape(orig_shape[:-1] + [out.shape[-1]])
