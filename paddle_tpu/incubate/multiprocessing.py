"""paddle.incubate.multiprocessing (reference
``python/paddle/incubate/multiprocessing/__init__.py`` + ``reductions.py``:
makes Tensors picklable across processes via shared-memory file descriptors
so DataLoader workers / spawn targets can pass tensors).

TPU-native: device arrays cannot share HBM across host processes; the
portable cross-process representation is host numpy. The reduction
registered here pickles a Tensor as (numpy bytes, dtype, stop_gradient) —
correctness-preserving, one host copy, matching how the framework's own
DataLoader workers already move data. API parity: this module re-exports
the stdlib multiprocessing surface after installing the reducers.
"""
from __future__ import annotations

import copyreg
from multiprocessing import *  # noqa: F401,F403 - reference re-exports mp
from multiprocessing import get_context, Process, Queue  # noqa: F401

import numpy as np


def _rebuild_tensor(arr, stop_gradient):
    from ..framework.tensor import Tensor

    t = Tensor(arr)
    t.stop_gradient = stop_gradient
    return t


def _reduce_tensor(t):
    return _rebuild_tensor, (np.asarray(t._value), bool(t.stop_gradient))


_installed = False


def _install_reductions():
    global _installed
    if _installed:
        return
    from ..framework.tensor import Parameter, Tensor

    copyreg.pickle(Tensor, _reduce_tensor)
    copyreg.pickle(Parameter, _reduce_tensor)
    _installed = True


_install_reductions()
