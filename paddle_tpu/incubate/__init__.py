"""paddle.incubate (reference ``python/paddle/incubate/``)."""
from . import autograd  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import nn  # noqa: F401
from . import autotune  # noqa: F401
from . import asp  # noqa: F401
from . import checkpoint  # noqa: F401
from . import multiprocessing  # noqa: F401
from .optimizer import DistributedFusedLamb, LookAhead, ModelAverage  # noqa: F401
