"""Automatic SParsity — 2:4 structured sparsity (reference
``python/paddle/incubate/asp/`` — ``asp.py decorate/prune_model``,
``supported_layer_list.py``, mask algorithms in ``utils.py``).

TPU-native: the 2:4 pattern (keep the 2 largest-|w| of every 4 along the
reduction dim) is computed as a boolean mask per supported weight;
``prune_model`` applies it once, and a ``decorate``-wrapped optimizer
re-applies it after every step so training stays inside the sparse support
(the reference's OptimizerWithSparsityGuarantee). The masked multiply is a
traced elementwise op, so ASP training jit-compiles like everything else.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..optimizer.optimizer import Optimizer

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density"]

_EXCLUDED: set[str] = set()
_MASKS: dict[str, jnp.ndarray] = {}


def set_excluded_layers(param_names, main_program=None):
    """Reference ``asp.py set_excluded_layers``: skip these params."""
    for n in param_names:
        _EXCLUDED.add(n)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x):
    """Fraction of nonzeros (reference ``asp.py calculate_density``)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float((arr != 0).sum() / arr.size)


def _mask_groups(flat: np.ndarray) -> np.ndarray:
    """Per group of 4 along the last axis keep the top-2 |w|."""
    cols = flat.shape[-1]
    pad = (-cols) % 4
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    g = flat.reshape(flat.shape[0], -1, 4)
    order = np.argsort(-np.abs(g), axis=-1)
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[..., :2], True, axis=-1)
    return mask.reshape(flat.shape)[:, :cols]


def _mask_2to4_1d(w: np.ndarray) -> np.ndarray:
    """mask_1d along the REDUCTION dim (what 2:4 sparse matmul hardware
    contracts over): Linear weight is [in, out] -> groups run along `in`;
    Conv weight is [cout, cin, kh, kw] -> groups along cin*kh*kw."""
    if w.ndim == 2:
        # [in, out]: reduction is axis 0
        return _mask_groups(w.T).T
    # conv-style [cout, ...reduction...]
    return _mask_groups(w.reshape(w.shape[0], -1)).reshape(w.shape)


def _supported_params(model):
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (Linear, Conv2D)):
            w = getattr(layer, "weight", None)
            if w is not None and w.name not in _EXCLUDED and w.ndim >= 2:
                yield w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute and apply 2:4 masks (reference ``asp.py prune_model``).
    Returns {param_name: mask}."""
    if (n, m) != (2, 4):
        raise NotImplementedError("only 2:4 sparsity is supported")
    if mask_algo not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    out = {}
    for p in _supported_params(model):
        mask = _mask_2to4_1d(np.asarray(p._value, dtype=np.float32))
        m_arr = jnp.asarray(mask, p._value.dtype)
        p._value = p._value * m_arr
        if with_mask:
            _MASKS[p.name] = m_arr
        out[p.name] = m_arr
    return out


class OptimizerWithSparsityGuarantee:
    """Re-applies the pruning masks after every step (reference
    ``asp.py OptimizerWithSparsityGuarantee``)."""

    def __init__(self, optimizer):
        self._inner_opt = optimizer

    def step(self):
        self._inner_opt.step()
        for p in self._inner_opt._parameter_list or []:
            m = _MASKS.get(p.name)
            if m is not None:
                p._value = p._value * m

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero=set_to_zero)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        self.clear_grad()

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)


def decorate(optimizer):
    """Reference ``asp.py decorate``."""
    if not isinstance(optimizer, Optimizer):
        raise TypeError("decorate expects an Optimizer")
    return OptimizerWithSparsityGuarantee(optimizer)
