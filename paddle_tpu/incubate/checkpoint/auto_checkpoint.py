"""Auto-checkpoint for failure resume (reference
``fluid/incubate/checkpoint/auto_checkpoint.py:71`` AutoCheckpointChecker —
an epoch-range hook that snapshots training state and, after a restart,
fast-forwards the epoch loop to the last saved epoch).

TPU-native redesign: the reference serializes ProgramDesc + persistables to
HDFS; here the state is the registered Layers'/Optimizers' state_dicts
saved with the framework's own checkpoint format to a local/NFS dir. The
user-facing contract is identical::

    acp.register(model=model, optimizer=opt)
    for epoch in acp.train_epoch_range(10, save_dir="ckpt"):
        train_one_epoch()

On a fresh run epochs 0..9 execute; if the job dies after epoch 3, the
rerun resumes at epoch 4 with restored state.
"""
from __future__ import annotations

import json
import os

__all__ = ["register", "train_epoch_range", "reset"]

_registered = {"layers": [], "optimizers": []}


def register(model=None, optimizer=None, **named):
    """Register the stateful objects whose state the checkpointer owns."""
    from ...nn.layer.layers import Layer
    from ...optimizer.optimizer import Optimizer

    objs = [model, optimizer] + list(named.values())
    for o in objs:
        if o is None:
            continue
        if isinstance(o, Layer):
            _registered["layers"].append(o)
        elif isinstance(o, Optimizer) or hasattr(o, "state_dict"):
            _registered["optimizers"].append(o)
        else:
            raise TypeError(f"cannot checkpoint object of type {type(o)!r}")


def reset():
    _registered["layers"].clear()
    _registered["optimizers"].clear()


def _marker_path(save_dir):
    return os.path.join(save_dir, "acp_meta.json")


def _save(save_dir, epoch):
    from ...framework.io import atomic_write, save as psave

    os.makedirs(save_dir, exist_ok=True)
    # state files FIRST — each atomic (tmp+fsync+replace, framework.io) —
    # and only then the marker, also atomic + fsynced: the marker can never
    # name an epoch whose state files are missing or partial, and a crash
    # anywhere leaves the previous epoch resumable (the reference's
    # checkpoint epoch ordering)
    state_files = []
    for i, l in enumerate(_registered["layers"]):
        state_files.append(os.path.join(save_dir, f"layer{i}.pdparams"))
        psave(l.state_dict(), state_files[-1])
    for i, o in enumerate(_registered["optimizers"]):
        state_files.append(os.path.join(save_dir, f"opt{i}.pdopt"))
        psave(o.state_dict(), state_files[-1])
    marker = {"epoch": epoch,
              "state_files": [os.path.basename(p) for p in state_files]}
    atomic_write(_marker_path(save_dir),
                 lambda f: f.write(json.dumps(marker).encode()))


def _restore(save_dir):
    from ...framework.io import load as pload

    marker = _marker_path(save_dir)
    if not os.path.exists(marker):
        return -1
    with open(marker) as f:
        epoch = json.load(f)["epoch"]
    for i, l in enumerate(_registered["layers"]):
        l.set_state_dict(pload(os.path.join(save_dir, f"layer{i}.pdparams")))
    for i, o in enumerate(_registered["optimizers"]):
        o.set_state_dict(pload(os.path.join(save_dir, f"opt{i}.pdopt")))
    return epoch


def train_epoch_range(max_epoch_num, save_dir="auto_checkpoint",
                      save_checkpoint_inter=1):
    """Generator over epochs with restore-on-entry and save-per-epoch."""
    last = _restore(save_dir)
    for epoch in range(last + 1, max_epoch_num):
        yield epoch
        if (epoch + 1) % save_checkpoint_inter == 0 or epoch == max_epoch_num - 1:
            _save(save_dir, epoch)
