"""paddle.incubate.checkpoint (reference ``fluid/incubate/checkpoint/``)."""
from . import auto_checkpoint  # noqa: F401
