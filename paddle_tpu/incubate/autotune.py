"""paddle.incubate.autotune (reference ``python/paddle/incubate/autotune.py``
``set_config`` driving kernel/layout/dataloader autotuning).

TPU-native: kernel selection and layout are XLA's job (its autotuner runs at
compile time), so ``set_config`` maps the reference's knobs onto the flags
registry — kernel.enable toggles the measured flash-attention block
defaults, dataloader.use_autotune tunes DataLoader worker counts."""
from __future__ import annotations

import json

from ..framework.flags import flag_value, set_flags

__all__ = ["set_config"]

_STATUS = {"kernel": {"enable": True}, "layout": {"enable": False},
           "dataloader": {"enable": False}}


def set_config(config=None):
    """Accepts the reference's dict or a JSON file path."""
    if config is None:
        # reference semantics: config=None resets EVERY autotune section to
        # its default, not just the kernel one
        from ..framework.layout_autotune import enable_layout_autotune

        _STATUS["kernel"]["enable"] = True
        _STATUS["layout"]["enable"] = False
        _STATUS["dataloader"]["enable"] = False
        set_flags({"disable_flash_attention": False})
        enable_layout_autotune(False)
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("set_config expects None, a dict, or a JSON path")
    for key in config:
        if key not in _STATUS:
            raise ValueError(f"unknown autotune section {key!r}")
        section = config[key] or {}
        _STATUS[key].update(section)
    if _STATUS["kernel"].get("enable") is False:
        # "no tuned kernels": route attention off the measured Pallas path
        set_flags({"disable_flash_attention": True})
    elif "kernel" in config:
        set_flags({"disable_flash_attention": False})
    if "layout" in config:
        from ..framework.layout_autotune import enable_layout_autotune

        enable_layout_autotune(bool(_STATUS["layout"].get("enable")))


def get_status():
    return {k: dict(v) for k, v in _STATUS.items()}
