"""Dygraph→XLA functionalization: the "executor" of this framework.

Reference analogue: ``paddle.jit.to_static`` (AST transpile to ProgramDesc,
``dygraph_to_static/program_translator.py:991``) executed by
InterpreterCore (``framework/new_executor/interpretercore.h:38``).

TPU-native redesign: there is no IR of our own and no interpreter. A python
step function (forward+backward+optimizer.step, written in eager dygraph
style) is *traced by jax.jit* — the tape's vjp closures are jax-traceable, so
the entire step lowers to ONE fused XLA program. Mutable framework state
(Layer params/buffers, optimizer accumulators, the RNG key) is threaded as an
explicit donated pytree: functional on the inside, mutable on the outside.

This replaces, in one mechanism: ProgramDesc construction, the op-by-op
executors, stream-aware scheduling, per-op GC, gradient fusion (Reducer
buckets), and fused-optimizer ops — XLA does the scheduling and fusion.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from ..framework import random as rnd
from ..profiler import telemetry as _telemetry
from ..profiler import tracing as _tracing
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from ..optimizer.optimizer import Optimizer

__all__ = ["functionalize", "CompiledStep", "to_static", "not_to_static"]

_analysis_mod = None
_devprof_mod = None


def _analysis():
    """Cached handle to paddle_tpu.analysis (lazy: keep the graph-lint
    subsystem off the import path and the per-call flag check at attribute-
    access cost)."""
    global _analysis_mod
    if _analysis_mod is None:
        from .. import analysis as _a

        _analysis_mod = _a
    return _analysis_mod


def _devprof():
    """Cached handle to paddle_tpu.profiler.devprof (lazy, same rationale
    as :func:`_analysis`)."""
    global _devprof_mod
    if _devprof_mod is None:
        from ..profiler import devprof as _d

        _devprof_mod = _d
    return _devprof_mod


def _layer_refs(layer: Layer):
    refs = {"params": {}, "buffers": {}}
    for name, p in layer.named_parameters():
        refs["params"][name] = p
    for name, b in layer.named_buffers():
        if b is not None:
            refs["buffers"][name] = b
    return refs


class _StateSpec:
    """Collects and swaps mutable state for a set of Layers/Optimizers."""

    def __init__(self, stateful):
        self.layers = [s for s in stateful if isinstance(s, Layer)]
        self.optimizers = [s for s in stateful if isinstance(s, Optimizer)]
        # anything else exposing the _state_pytree protocol (e.g. GradScaler)
        self.others = [
            s
            for s in stateful
            if not isinstance(s, (Layer, Optimizer)) and hasattr(s, "_state_pytree")
        ]
        self._refs = [_layer_refs(l) for l in self.layers]
        # materialize optimizer accumulators BEFORE the first snapshot: lazy
        # creation inside the first traced step changes the state pytree
        # between calls 1 and 2 and forces a second trace+compile (the
        # Adam/AdamW double-trace PR 2's telemetry measured; graph-lint's
        # retrace-state-structure rule catches the pattern statically).
        # "others" covered too: sharded-optimizer wrappers delegate the
        # method to their inner Optimizer via __getattr__.
        for o in self.optimizers + self.others:
            ensure = getattr(o, "_ensure_accumulators", None)
            if ensure is not None:
                ensure()

    def snapshot(self):
        # read through the refs cached at construction instead of re-walking
        # named_parameters() every step (the recursive layer traversal showed
        # up as ~2 ms/step host time in the device profile)
        return {
            "layers": [
                {"params": {n: p._value for n, p in refs["params"].items()},
                 "buffers": {n: b._value for n, b in refs["buffers"].items()}}
                for refs in self._refs
            ],
            "optimizers": [o._state_pytree() for o in self.optimizers],
            "others": [o._state_pytree() for o in self.others],
            "rng": rnd.default_generator.get_state(),
        }

    def install(self, tree):
        for refs, st in zip(self._refs, tree["layers"]):
            for name, p in refs["params"].items():
                p._value = st["params"][name]
            for name, b in refs["buffers"].items():
                b._value = st["buffers"][name]
        for o, st in zip(self.optimizers, tree["optimizers"]):
            o._load_state_pytree(st)
        for o, st in zip(self.others, tree.get("others", [])):
            o._load_state_pytree(st)
        rnd.default_generator.set_state(tree["rng"])

    def clear_grads(self):
        for refs in self._refs:
            for p in refs["params"].values():
                p._grad = None
                p._grad_node = None
                p._out_slot = 0


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def _wrap(x, stop_gradient=True):
    if isinstance(x, (jax.Array,)) or isinstance(x, jax.core.Tracer):
        return Tensor(x, stop_gradient=stop_gradient)
    return x


class _Dyn:
    """Placeholder marking a dynamic (traced) leaf inside the static spec."""

    __slots__ = ()

    def __repr__(self):
        return "<dyn>"


_DYN = _Dyn()


def _is_dynamic_leaf(leaf):
    """Traced-array leaf vs static python attribute. Python scalars/strings
    are STATIC — they are op attributes in the reference's ProgramDesc, not
    tensors — so a new value recompiles rather than becoming a tracer (this
    is what lets python control flow on them unroll at trace time).
    ``ShapeDtypeStruct`` counts as dynamic so ``lower``/``analyze``/devprof
    harvesting can run from shapes alone, without live (possibly donated)
    buffers."""
    import numpy as np

    return (isinstance(leaf, (jax.Array, np.ndarray, np.generic,
                              jax.ShapeDtypeStruct))
            or _is_tracer_val(leaf))


def _partition_args(args, kwargs):
    """Split the (args, kwargs) tree into traced array leaves and a hashable
    static remainder (see ``_is_dynamic_leaf`` for the boundary)."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    dyn = []
    spec = []
    for leaf in leaves:
        if _is_dynamic_leaf(leaf):
            dyn.append(leaf)
            spec.append(_DYN)
        else:
            spec.append(leaf)
    try:
        hash(tuple(spec))
        static = (treedef, tuple(spec))
    except TypeError:
        # unhashable static leaf: degrade to tracing everything
        static = None
    if static is None:
        return leaves, (treedef, None)
    return dyn, static


def _is_tracer_val(x):
    from ..framework.tensor import _is_tracer

    return _is_tracer(x)


def _arg_path_str(path):
    """(args, kwargs) pytree path -> the user-facing ``args[i]…`` /
    ``kwargs['k']…`` form used by ``donate_inputs=[…]`` and the graph-lint
    findings."""
    head, rest = path[0], tuple(path[1:])
    base = "args" if getattr(head, "idx", 0) == 0 else "kwargs"
    return base + jax.tree_util.keystr(rest)


class CompiledStep:
    """A cached compiled XLA step (≙ the reference's compiled-program cache in
    ``fluid/executor.py`` + InterpreterCore instruction list)."""

    def __init__(self, fn, stateful=(), donate_state=True, donate_inputs=False,
                 static_argnames=None):
        self.fn = fn
        self.name = getattr(fn, "__name__", type(fn).__name__)
        # set True by pure() — which only executes while jax traces, i.e.
        # on a compile-cache miss — so __call__ can attribute its wall time
        # to the `compile` phase instead of `dispatch`
        self._trace_marker = {"traced": False}
        self.spec = _StateSpec(stateful)
        self._pure = self._build_pure()
        # donate_inputs: staged single-use batches (io.DeviceLoader) hand
        # their HBM back to XLA for the step's own temporaries. Contract:
        # donated inputs are CONSUMED — the caller must not touch a batch
        # after passing it in. Besides True/False it accepts an iterable of
        # argument pytree paths ("args[0]", "kwargs['x']…" — the exact form
        # graph-lint's hbm-undonated-input finding prints) to donate only
        # those leaves.
        if isinstance(donate_inputs, bool):
            self._donate_paths = None
            self.donate_inputs = donate_inputs
        else:
            self._donate_paths = tuple(str(p) for p in donate_inputs)
            self.donate_inputs = bool(self._donate_paths)
        self._donate_mask_cache = {}
        self.donate_state = bool(donate_state)
        donate = (0,) if donate_state else ()
        # argnum 1 is the donated-leaves list: empty unless donation was
        # requested, so donating it unconditionally is free
        donate = donate + (1,)
        self._jitted = jax.jit(
            self._pure, donate_argnums=donate, static_argnums=(3,),
            static_argnames=static_argnames
        )

    def _build_pure(self):
        spec = self.spec
        fn = self.fn
        marker = self._trace_marker

        def pure(state, dyn_donated, dyn_kept, static_spec):
            marker["traced"] = True
            treedef, static_leaves, don_mask = static_spec
            it_d, it_k, it_m = iter(dyn_donated), iter(dyn_kept), iter(don_mask)
            if static_leaves is None:
                leaves = [next(it_d) if next(it_m) else next(it_k)
                          for _ in range(len(don_mask))]
            else:
                leaves = [((next(it_d) if next(it_m) else next(it_k))
                           if s is _DYN else s)
                          for s in static_leaves]
            args, kwargs = jax.tree_util.tree_unflatten(treedef, leaves)
            prev = spec.snapshot()
            spec.install(state)
            try:
                t_args = jax.tree_util.tree_map(_wrap, args)
                t_kwargs = jax.tree_util.tree_map(_wrap, kwargs)
                out = fn(*t_args, **t_kwargs)
                out_arrays = jax.tree_util.tree_map(_unwrap, out)
                new_state = spec.snapshot()
            finally:
                spec.clear_grads()
                spec.install(prev)
            return out_arrays, new_state

        return pure

    def _donation_mask(self, tree, treedef, spec_t, n_dyn):
        """Per-dyn-leaf donate flags. Bool modes are trivial; path mode
        resolves ``self._donate_paths`` against the leaf paths once per
        (treedef, spec) signature and caches the mask."""
        if self._donate_paths is None:
            return ((True,) * n_dyn if self.donate_inputs
                    else (False,) * n_dyn)
        key = (treedef, spec_t) if spec_t is not None else None
        mask = self._donate_mask_cache.get(key) if key is not None else None
        if mask is None or len(mask) != n_dyn:
            flags = []
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                if spec_t is not None and not _is_dynamic_leaf(leaf):
                    continue
                p = _arg_path_str(path)
                flags.append(any(p == d or p.startswith(d)
                                 for d in self._donate_paths))
            mask = tuple(flags)
            if key is not None:
                self._donate_mask_cache[key] = mask
        return mask

    def _prepare(self, args, kwargs):
        arr_args = jax.tree_util.tree_map(_unwrap, args)
        arr_kwargs = jax.tree_util.tree_map(_unwrap, kwargs)
        dyn, (treedef, spec_t) = _partition_args(arr_args, arr_kwargs)
        mask = self._donation_mask((arr_args, arr_kwargs), treedef, spec_t,
                                   len(dyn))
        dyn_donated = [l for l, m in zip(dyn, mask) if m]
        dyn_kept = [l for l, m in zip(dyn, mask) if not m]
        return dyn_donated, dyn_kept, (treedef, spec_t, mask)

    def _invoke(self, args, kwargs):
        from ..fault import inject

        state = self.spec.snapshot()
        dyn_donated, dyn_kept, static = self._prepare(args, kwargs)
        try:
            inject.check("dispatch")  # oom/error injection (devprof tests)
            out_arrays, new_state = self._jitted(state, dyn_donated, dyn_kept,
                                                 static)
        except Exception as e:
            if _devprof().is_oom_error(e):
                # device OOM at dispatch: dump the ranked forensics
                # (memory breakdown, donation status, batch/state shapes)
                # before re-raising the original XLA error
                try:
                    _devprof().dump_oom_forensics(self, e, args, kwargs)
                except Exception:  # noqa: BLE001 - never mask the OOM
                    pass
            raise
        self.spec.install(new_state)
        self.spec.clear_grads()
        return jax.tree_util.tree_map(lambda a: _wrap(a), out_arrays)

    def __call__(self, *args, **kwargs):
        if (_analysis().lint_on_compile_enabled()
                and not getattr(self, "_autolint_done", False)):
            # opt-in warn-on-compile: lint BEFORE the first execution — the
            # retrace hazards (lazily-materialized optimizer state) are only
            # visible in the PRE-step state pytree; after one real step the
            # state has stabilized and the defect is invisible statically
            _analysis().autolint(self, args, kwargs, enabled=True)
        tm_on = _telemetry.enabled()
        # trace-context compile attribution: only worth timing when a span
        # is actually current (a request's prefill, a train step, ...)
        tr_on = _tracing.enabled() and _tracing.current_span() is not None
        if not tm_on and not tr_on:
            return self._invoke(args, kwargs)
        marker = self._trace_marker
        marker["traced"] = False
        # capture the batch signature (shapes only) BEFORE the call: if it
        # traces, devprof harvests against it — the real buffers may be
        # donated/consumed by then. Skipped once the harvest has run.
        sig = None
        if tm_on and not getattr(self, "_devprof_done", False) \
                and _devprof().auto_harvest_enabled():
            try:
                sig = _devprof()._shape_only((args, kwargs))
            except Exception:
                sig = None
        t0 = time.perf_counter_ns()
        out = self._invoke(args, kwargs)
        t1 = time.perf_counter_ns()
        if marker["traced"]:
            if tm_on:
                # traced this call: wall time is dominated by trace+XLA
                # compile; repeated hits here for one step name = shape/
                # dtype churn
                tm = _telemetry.get_telemetry()
                tm.note_compile(self.name, t0, t1)
                if sig is not None:
                    # first compile: harvest the DeviceCostReport (memory/
                    # cost/comm ground truth) into the telemetry registry
                    _devprof().maybe_harvest_on_compile(self, sig[0], sig[1])
            if tr_on:
                # a `compile` child span under the current request/train
                # span: the trace export shows who paid this compile
                idx = (_telemetry.get_telemetry().compile_counts()
                       .get(self.name) if tm_on else None)
                _tracing.note_compile(self.name, t0, t1, compile_index=idx)
        elif tm_on:
            # cache hit: host-side enqueue of the async device execution
            _telemetry.get_telemetry().add_phase("dispatch", t0, t1)
        return out

    def analyze(self, *args, **kwargs):
        """Statically lint this step against the example batch — abstract
        trace only, nothing runs on device. Returns a
        :class:`paddle_tpu.analysis.LintReport`."""
        return _analysis().lint_step(self, *args, **kwargs)

    def device_report(self, *args, **kwargs):
        """Harvest the compile-time :class:`~paddle_tpu.profiler.devprof.
        DeviceCostReport` for this step against the example batch: FLOPs,
        bytes accessed, the HBM peak breakdown, and per-mesh-axis
        collective bytes. Arguments are reduced to shapes before lowering,
        so donated/consumed batches are safe to pass."""
        return _devprof().device_report(self, *args, **kwargs)

    def lower(self, *args, **kwargs):
        state = self.spec.snapshot()
        dyn_donated, dyn_kept, static = self._prepare(args, kwargs)
        return self._jitted.lower(state, dyn_donated, dyn_kept, static)


def functionalize(fn=None, *, stateful=(), donate_state=True,
                  donate_inputs=False):
    """Decorator: compile a dygraph-style step function into one XLA program.

        @paddle_tpu.jit.functionalize(stateful=[model, opt])
        def train_step(x, y):
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

    ``donate_inputs=True`` additionally donates the batch arrays (see
    ``CompiledStep``): use with single-use staged batches only.
    ``donate_inputs=["args[0]"]`` donates just the named argument pytree
    paths — the exact strings graph-lint's ``hbm-undonated-input`` finding
    prints.
    """

    def deco(f):
        step = CompiledStep(f, stateful=stateful, donate_state=donate_state,
                            donate_inputs=donate_inputs)
        functools.update_wrapper(step, f, updated=())
        return step

    return deco(fn) if fn is not None else deco


class StaticFunction:
    """`@to_static` on a Layer's forward / plain function (inference path):
    no in-place state writes expected; buffers treated read-only."""

    def __init__(self, fn, layer=None):
        self.fn = fn
        self.layer = layer
        self._compiled = None

    def _ensure(self):
        if self._compiled is None:
            stateful = [self.layer] if self.layer is not None else []
            self._compiled = CompiledStep(self.fn, stateful=stateful, donate_state=False)
        return self._compiled

    def __call__(self, *args, **kwargs):
        from jax._src import core as _jcore

        if not _jcore.trace_state_clean():
            # already inside a trace (an enclosing CompiledStep, or this
            # function calling itself): inline into the outer program — the
            # reference likewise inlines nested to_static functions into one
            # ProgramDesc rather than nesting executors
            return self.fn(*args, **kwargs)
        return self._ensure()(*args, **kwargs)

    @property
    def code(self):
        import inspect

        return inspect.getsource(self.fn)

    def concrete_program(self, *args, **kwargs):
        return self._ensure().lower(*args, **kwargs)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """paddle.jit.to_static — jax.jit tracing + AST control-flow conversion.

    Tensor-dependent Python ``if``/``while``/``for range`` are rewritten by
    :mod:`paddle_tpu.jit.dy2static` onto ``lax.cond``/``lax.while_loop``
    (the reference's dygraph_to_static AST transpile, retargeted); constructs
    outside the transform contract (early return under a tensor condition)
    keep Python semantics and raise jax's concretization error under trace."""
    from . import dy2static

    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            fwd = dy2static.convert_to_static(type(layer).forward)
            sf = StaticFunction(lambda *a, **k: fwd(layer, *a, **k), layer=layer)
            layer.forward = sf
            return layer
        return StaticFunction(dy2static.convert_to_static(fn))

    return deco(function) if function is not None else deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn
