"""paddle.jit.save / paddle.jit.load.

Reference: serialized ProgramDesc + params (``paddle/fluid/jit/serializer.cc``,
``python/paddle/fluid/dygraph/jit.py``). TPU-native: the portable artifact is
a *StableHLO export* (jax.export) of the traced forward plus a pickled
state_dict — loadable without the original python class (TranslatedLayer)."""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.io import load as _pload
from ..framework.io import save as _psave
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["save", "load", "TranslatedLayer", "InputSpec"]


class InputSpec:
    """reference ``paddle/static/input.py InputSpec``."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def _to_example(self, sym_prefix="d"):
        from ..framework.dtype import convert_dtype

        dt = convert_dtype(self.dtype)
        if any(s is None or s < 0 for s in self.shape):
            # dynamic dims export as jax.export symbolic dimensions, so the
            # loaded program accepts any size (e.g. variable batch)
            dims = []
            for i, s in enumerate(self.shape):
                if s is None or s < 0:
                    dims.append(jax.export.symbolic_shape(f"{sym_prefix}{i}")[0])
                else:
                    dims.append(int(s))
            return jax.ShapeDtypeStruct(tuple(dims), dt)
        return jnp.zeros([int(s) for s in self.shape], dt)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def save(layer, path, input_spec=None, **configs):
    """Export layer.forward as StableHLO + weights at `path`(.pdmodel/.pdiparams)."""
    layer.eval()
    if input_spec is None:
        raise ValueError("paddle.jit.save requires input_spec on TPU build")
    examples = [
        s._to_example(sym_prefix=f"s{i}_") if isinstance(s, InputSpec) else jnp.asarray(np.asarray(s.numpy() if isinstance(s, Tensor) else s))
        for i, s in enumerate(input_spec)
    ]
    params = {k: v._value for k, v in layer.state_dict().items()}

    def pure_forward(params, *inputs):
        # install weights functionally into a stateless call
        sd = layer.state_dict()
        old = {k: t._value for k, t in sd.items()}
        for k, t in sd.items():
            t._value = params[k]
        try:
            out = layer(*[Tensor(i) for i in inputs])
        finally:
            for k, t in sd.items():
                t._value = old[k]
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out
        )

    jitted = jax.jit(pure_forward)
    exported = jax.export.export(jitted)(params, *examples)
    blob = exported.serialize()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    _psave({k: Tensor(v) for k, v in params.items()}, path + ".pdiparams")
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump({"n_inputs": len(examples)}, f)


def save_traced(fn, input_specs, path):
    """Export a plain traced function (no Layer state) as StableHLO — the
    serialization primitive behind ``static.save_inference_model``."""

    def pure(params, *inputs):
        del params
        return fn(*inputs)

    jitted = jax.jit(pure)
    exported = jax.export.export(jitted)({}, *input_specs)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    _psave({}, path + ".pdiparams")
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump({"n_inputs": len(input_specs)}, f)
    return path


class TranslatedLayer(Layer):
    """A loaded StableHLO program behaving like a Layer
    (reference ``fluid/dygraph/io.py TranslatedLayer``)."""

    def __init__(self, exported, params):
        super().__init__()
        self._exported = exported
        self._params_tree = {k: (v._value if isinstance(v, Tensor) else jnp.asarray(v)) for k, v in params.items()}

    def forward(self, *inputs):
        arrays = [i._value if isinstance(i, Tensor) else jnp.asarray(i) for i in inputs]
        out = self._exported.call(self._params_tree, *arrays)
        return jax.tree_util.tree_map(Tensor, out)

    def state_dict(self, *a, **k):
        return {k2: Tensor(v) for k2, v in self._params_tree.items()}


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    params = _pload(path + ".pdiparams")
    return TranslatedLayer(exported, params)
