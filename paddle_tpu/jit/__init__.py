"""paddle.jit equivalent — compiled-step cache instead of ProgramDesc executor."""
from . import dy2static  # noqa: F401
from .functionalize import (  # noqa: F401
    CompiledStep,
    StaticFunction,
    functionalize,
    not_to_static,
    to_static,
)
from .save_load import InputSpec, TranslatedLayer, load, save  # noqa: F401


def enable_to_static(flag=True):
    """Toggle the dy2static AST conversion globally (reference
    ``paddle.jit.enable_to_static``)."""
    dy2static.enable(flag)


class ProgramTranslator:
    """compat shim (reference program_translator.py ProgramTranslator)."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        dy2static.enable(flag)
