"""paddle.jit equivalent — compiled-step cache instead of ProgramDesc executor."""
from . import dy2static  # noqa: F401
from .functionalize import (  # noqa: F401
    CompiledStep,
    StaticFunction,
    functionalize,
    not_to_static,
    to_static,
)
from .save_load import InputSpec, TranslatedLayer, load, save  # noqa: F401


def enable_to_static(flag=True):
    """Toggle the dy2static AST conversion globally (reference
    ``paddle.jit.enable_to_static``)."""
    dy2static.enable(flag)


class ProgramTranslator:
    """compat shim (reference program_translator.py ProgramTranslator)."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        dy2static.enable(flag)

_CODE_LEVEL = [0]


def set_code_level(level=100):
    """reference ``jit/logging_utils set_code_level``: controls how much
    dy2static-transformed code is printed."""
    _CODE_LEVEL[0] = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    """reference ``jit/logging_utils set_verbosity``."""
    _CODE_LEVEL[0] = int(level)


class TracedLayer:
    """reference ``fluid/dygraph/jit.py TracedLayer``: a traced module you
    can call and save (here: a thin adapter over jit.save's traced
    artifact)."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._inputs = inputs

    @staticmethod
    def trace(layer, inputs):
        tl = TracedLayer(layer, inputs)
        outs = layer(*inputs)
        return outs, tl

    def __call__(self, *args):
        return self._layer(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        from .save_load import InputSpec, save

        specs = [InputSpec(list(i.shape), str(i.dtype).split(".")[-1])
                 for i in self._inputs]
        save(self._layer, path, input_spec=specs)
        return path
