"""Dygraph-to-static AST transformation: Python control flow → lax control flow.

Reference analogue: ``python/paddle/fluid/dygraph/dygraph_to_static/``
(``program_translator.py:991`` ProgramTranslator + ``ifelse_transformer.py``,
``loop_transformer.py``, ``logical_transformer.py``).  The reference rewrites
Python source so that tensor-dependent ``if``/``while``/``for`` become
``conditional_block``/``while`` ops in a ProgramDesc.

TPU-native redesign: the rewrite targets are the framework's dual-mode
control-flow primitives (:func:`paddle_tpu.static.nn.cond` /
:func:`~paddle_tpu.static.nn.while_loop`), which python-branch eagerly and
lower to ``lax.cond`` / ``lax.while_loop`` under a jit trace or static
Program recording.  Because those primitives already thread autograd through
``apply_op``, transformed control flow is differentiable in both modes —
there is no separate "static backward" pass to generate.

Mechanics (same shape as the reference's transformers):

- a tensor-dependent ``if`` becomes a pair of zero-arg branch closures over
  the enclosing frame plus ``get/set`` state accessors for every name the
  branches assign (``nonlocal``-threading, the reference's
  ``create_get_args_node``/``create_set_args_node`` pattern);
- ``while``/``for range`` become loop-body closures with the assigned names
  as loop-carried state;
- ``and``/``or``/``not`` become lazy converters that preserve Python
  short-circuit semantics when the operands are concrete.

Deliberate contract differences from the reference (documented, checked):

- ``return``/``break``/``continue`` inside a *tensor-dependent* block are
  not restructured; such statements leave the enclosing construct in plain
  Python form (correct eagerly, clear jax ConcretizationTypeError under
  trace).  The reference's ReturnTransformer covers these; here the
  functional jax style makes early-exit rewrites a poor trade.
- a name assigned under a tensor-dependent ``if`` must either exist before
  the ``if`` or be assigned in **both** branches (the reference raises the
  same class of error at ProgramDesc build time for undefined vars).
- a ``for range`` loop target that was undefined before the loop is seeded
  with ``start`` so a zero-trip *symbolic* loop stays well-defined inside
  the trace; plain Python would raise NameError when the target is read
  after a loop that never ran (``convert_for_range``).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["convert_to_static", "convert_ifelse", "convert_while", "convert_for_range"]


class _Undefined:
    """Sentinel for names not yet bound in the enclosing frame (reference
    ``dygraph_to_static/utils.py`` UndefinedVar)."""

    def __repr__(self):
        return "<undefined>"


UNDEF = _Undefined()

_enabled = True


def enable(flag=True):
    global _enabled
    _enabled = bool(flag)


def _tensor_mod():
    from ..framework import tensor as T

    return T


def _concrete_bool(v):
    """Python bool of v if it is concrete, else None (symbolic)."""
    from ..static.program import Variable
    T = _tensor_mod()

    if isinstance(v, Variable):
        return None
    if isinstance(v, T.Tensor):
        v = v._value
    if T._is_tracer(v):
        return None
    if isinstance(v, jax.Array):
        return bool(v)
    return bool(v)


def _is_arraylike(v):
    from ..static.program import Variable
    T = _tensor_mod()

    return isinstance(
        v, (T.Tensor, Variable, jax.Array, np.ndarray, int, float, bool, np.generic)
    ) or T._is_tracer(v)


def _as_tensor(v):
    from ..static.program import Variable
    T = _tensor_mod()

    if isinstance(v, (T.Tensor, Variable)):
        return v
    return T.Tensor(jnp.asarray(v))


# ---------------------------------------------------------------------------
# runtime converters (the reference's convert_operators.py)
# ---------------------------------------------------------------------------


def convert_ifelse(pred, true_fn, false_fn, get_state, set_state, names):
    """Runtime dispatch for a transformed ``if`` (reference
    ``convert_operators.py convert_ifelse``)."""
    t = _concrete_bool(pred)
    if t is not None:
        (true_fn if t else false_fn)()
        return

    from ..static import nn as snn

    init = list(get_state())
    thread = [i for i, v in enumerate(init) if _is_arraylike(v)]
    operands = [_as_tensor(init[i]) for i in thread]

    def _branch(fn, tag):
        def run(*vals):
            cur = list(init)
            for pos, v in zip(thread, vals):
                cur[pos] = v
            set_state(tuple(cur))
            fn()
            out = list(get_state())
            for name, v in zip(names, out):
                if v is UNDEF:
                    raise ValueError(
                        f"dy2static: variable {name!r} is not assigned in the "
                        f"{tag} branch of a tensor-dependent `if`; it must "
                        "either exist before the `if` or be assigned in both "
                        "branches"
                    )
                if not _is_arraylike(v):
                    raise TypeError(
                        f"dy2static: variable {name!r} is assigned a "
                        f"non-tensor value ({type(v).__name__}) inside a "
                        "tensor-dependent `if`; only tensor/number values can "
                        "be threaded through lax.cond"
                    )
            return tuple(_as_tensor(v) for v in out)

        return run

    out = snn.cond(pred, _branch(true_fn, "true"), _branch(false_fn, "false"),
                   operands=operands)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    set_state(tuple(out))


def convert_while(test_fn, body_fn, get_state, set_state, names):
    """Runtime dispatch for a transformed ``while`` (reference
    ``convert_operators.py convert_while_loop``)."""
    t = _concrete_bool(test_fn())
    if t is not None:
        while t:
            body_fn()
            t = _concrete_bool(test_fn())
            if t is None:
                raise ValueError(
                    "dy2static: `while` condition became tensor-symbolic "
                    "mid-loop; hoist the symbolic state into the condition "
                    "before the loop"
                )
        return

    from ..static import nn as snn

    init = list(get_state())
    for name, v in zip(names, init):
        if v is UNDEF:
            raise ValueError(
                f"dy2static: loop variable {name!r} must be defined before a "
                "tensor-dependent `while`"
            )
        if not _is_arraylike(v):
            raise TypeError(
                f"dy2static: loop variable {name!r} has non-tensor type "
                f"{type(v).__name__}; tensor-dependent `while` loops can only "
                "carry tensor/number state"
            )

    def cond_w(*vals):
        set_state(tuple(vals))
        return test_fn()

    def body_w(*vals):
        set_state(tuple(vals))
        body_fn()
        return tuple(_as_tensor(v) for v in get_state())

    out = snn.while_loop(cond_w, body_w, [_as_tensor(v) for v in init])
    if not isinstance(out, (tuple, list)):
        out = (out,)
    set_state(tuple(out))


def convert_for_range(range_args, body_fn, get_state, set_state, names,
                      target_first=True):
    """Transformed ``for i in range(...)``: python loop when the bounds are
    concrete, counter-carried ``lax.while_loop`` otherwise. The loop target
    is ``names[0]`` and is assigned by the body each iteration (so, as in
    plain Python, it holds the final index after the loop)."""
    args = [a.item() if hasattr(a, "item") and _concrete_bool(a) is not None
            else a for a in range_args]
    concrete = all(_concrete_bool(a) is not None or isinstance(a, (int, np.integer))
                   for a in args)
    # normalize to (start, stop, step)
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    else:
        start, stop, step = args

    if concrete:
        for i in range(int(start), int(stop), int(step)):
            body_fn(i)
        return

    if isinstance(step, (int, np.integer)) and step == 0:
        raise ValueError("range() arg 3 must not be zero")

    from ..framework.tensor import Tensor
    from ..static import nn as snn

    init = list(get_state())
    if target_first and names and init[0] is UNDEF:
        # the target is only ever written by the loop itself; seed it with
        # `start` so zero-trip symbolic loops still produce a defined value
        init[0] = jnp.asarray(getattr(start, "_value", start), jnp.int32)
    for name, v in zip(names, init):
        if v is UNDEF:
            raise ValueError(
                f"dy2static: loop variable {name!r} must be defined before a "
                "tensor-dependent `for`"
            )

    start_t = jnp.asarray(getattr(start, "_value", start), jnp.int32)
    stop_t = jnp.asarray(getattr(stop, "_value", stop), jnp.int32)
    step_t = jnp.asarray(getattr(step, "_value", step), jnp.int32)
    # python-range trip count, valid for either step sign
    trips = jnp.maximum(0, (stop_t - start_t + step_t
                            - jnp.sign(step_t)) // step_t)

    def cond_w(k, *vals):
        return Tensor(k._value < trips)

    def body_w(k, *vals):
        set_state(tuple(vals))
        body_fn(Tensor(start_t + k._value * step_t))
        new = tuple(_as_tensor(v) for v in get_state())
        return (Tensor(k._value + 1),) + new

    out = snn.while_loop(
        cond_w, body_w, [Tensor(jnp.asarray(0, jnp.int32))] + [_as_tensor(v) for v in init]
    )
    out = out if isinstance(out, (tuple, list)) else (out,)
    set_state(tuple(out[1:]))


def convert_logical_and(*fns):
    """Lazy ``and`` preserving Python short-circuit on concrete operands.
    Symbolic operands combine through the framework's logical_and op so the
    expression records in static mode and traces under jit."""
    from ..ops import logic

    for i, f in enumerate(fns):
        val = f()
        c = _concrete_bool(val)
        if c is None:
            res = _bool_tensor(val)
            for g in fns[i + 1:]:
                res = logic.logical_and(res, _bool_tensor(g()))
            return res
        if not c:
            return val
    return val


def convert_logical_or(*fns):
    from ..ops import logic

    for i, f in enumerate(fns):
        val = f()
        c = _concrete_bool(val)
        if c is None:
            res = _bool_tensor(val)
            for g in fns[i + 1:]:
                res = logic.logical_or(res, _bool_tensor(g()))
            return res
        if c:
            return val
    return val


def convert_logical_not(val):
    from ..ops import logic

    c = _concrete_bool(val)
    if c is None:
        return logic.logical_not(_bool_tensor(val))
    return not c


def _bool_tensor(v):
    """As a bool Tensor/Variable, via the recorded cast for symbolic args."""
    T = _tensor_mod()
    if not isinstance(v, T.Tensor):
        return T.Tensor(jnp.asarray(v).astype(jnp.bool_))
    if str(v.dtype).endswith("bool"):
        return v
    return v.astype("bool")


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef,
                ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


class _StoreCollector(ast.NodeVisitor):
    """Names assigned at THIS scope level (does not descend into nested
    function/class/comprehension scopes)."""

    def __init__(self):
        self.names = []

    def visit(self, node):
        if isinstance(node, _SCOPE_NODES):
            # the def's own name is a store in this scope
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                self._add(node.name)
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._add(node.id)
        super().generic_visit(node)

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)


def _assigned_names(stmts):
    c = _StoreCollector()
    for s in stmts:
        c.visit(s)
    return c.names


class _EarlyExitFinder(ast.NodeVisitor):
    """Detects return/break/continue at this scope level (not inside nested
    defs; break/continue inside nested loops don't count)."""

    def __init__(self):
        self.has_return = False
        self.has_break = False

    def visit(self, node):
        if isinstance(node, _SCOPE_NODES):
            return
        if isinstance(node, ast.Return):
            self.has_return = True
        if isinstance(node, (ast.Break, ast.Continue)):
            self.has_break = True
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            # break/continue inside belong to that loop; returns still escape
            for s in node.body + node.orelse:
                sub = _EarlyExitFinder()
                sub.visit(s)
                self.has_return = self.has_return or sub.has_return
            return
        super().generic_visit(node)


def _blocks_transform(stmts):
    f = _EarlyExitFinder()
    for s in stmts:
        f.visit(s)
    return f.has_return or f.has_break


class _LogicalTransformer(ast.NodeTransformer):
    """``and``/``or``/``not`` → lazy converters. Applied ONLY inside
    ``if``/``while`` test expressions (reference logical_transformer.py
    converts everywhere; restricting to tests preserves Python's
    value-returning `x or default` idiom in ordinary expressions)."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("_jst.convert_logical_and" if isinstance(node.op, ast.And)
              else "_jst.convert_logical_or")
        lambdas = [ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=v) for v in node.values]
        (call,) = _parse_stmts(f"{fn}()")
        call.value.args = lambdas
        return ast.copy_location(call.value, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        (call,) = _parse_stmts("_jst.convert_logical_not(0)")
        call.value.args[0] = node.operand
        return ast.copy_location(call.value, node)

    # do not descend into nested lambdas' bodies beyond normal semantics
    def visit_Lambda(self, node):
        return node


def _convert_test(expr):
    new = _LogicalTransformer().visit(expr)
    ast.fix_missing_locations(new)
    return new


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------


def _parse_stmts(src):
    return ast.parse(textwrap.dedent(src)).body


class ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False

    def _n(self):
        self.counter += 1
        return self.counter

    # -- helpers ------------------------------------------------------------

    def _state_defs(self, outs, n):
        """try-bind each name (so `nonlocal` resolves) + get/set accessors."""
        stmts = []
        for name in outs:
            stmts += _parse_stmts(
                f"try:\n    {name} = {name}\n"
                f"except (NameError, UnboundLocalError):\n    {name} = _jst.UNDEF\n"
            )
        nl = f"nonlocal {', '.join(outs)}" if outs else "pass"
        tup = ", ".join(outs) + ("," if len(outs) == 1 else "")
        get_src = f"def _pt_get_{n}():\n    return ({tup})\n"
        set_src = (
            f"def _pt_set_{n}(_pt_vals):\n    {nl}\n    ({tup}) = _pt_vals\n"
            if outs else f"def _pt_set_{n}(_pt_vals):\n    pass\n"
        )
        if not outs:
            get_src = f"def _pt_get_{n}():\n    return ()\n"
        stmts += _parse_stmts(get_src) + _parse_stmts(set_src)
        return stmts

    def _body_fn(self, name, outs, body, params=""):
        nl = [f"    nonlocal {', '.join(outs)}"] if outs else []
        src = f"def {name}({params}):\n" + "\n".join(nl + ["    pass"])
        (fdef,) = _parse_stmts(src)
        fdef.body = fdef.body[:-1] + (body if body else [ast.Pass()])
        return fdef

    # -- visitors -----------------------------------------------------------

    @staticmethod
    def _outs(stmts, exclude=()):
        """Names the block assigns, minus generated helpers (nested transforms
        already rewrote inner nodes, planting _pt_* defs in the block)."""
        outs = [o for o in _assigned_names(stmts)
                if not o.startswith("_pt_") and o not in exclude]
        # dunder-prefixed locals would be threaded incorrectly — bail the
        # whole node (rare; keeps semantics over coverage)
        if any(o.startswith("__") for o in outs):
            return None
        return outs

    def visit_If(self, node):
        self.generic_visit(node)
        if _blocks_transform(node.body) or _blocks_transform(node.orelse):
            return node
        outs = self._outs(node.body + node.orelse)
        if outs is None:
            return node
        n = self._n()
        self.changed = True
        stmts = self._state_defs(outs, n)
        stmts.append(self._body_fn(f"_pt_true_{n}", outs, node.body))
        stmts.append(self._body_fn(f"_pt_false_{n}", outs, node.orelse))
        names_lit = repr(tuple(outs))
        (call,) = _parse_stmts(
            f"_jst.convert_ifelse(_pt_c, _pt_true_{n}, _pt_false_{n}, "
            f"_pt_get_{n}, _pt_set_{n}, {names_lit})"
        )
        # splice the real test expression in place of the placeholder name
        call.value.args[0] = _convert_test(node.test)
        assign = ast.copy_location(call, node)
        return [ast.copy_location(s, node) for s in stmts] + [assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _blocks_transform(node.body):
            return node
        outs = self._outs(node.body)
        if outs is None:
            return node
        n = self._n()
        self.changed = True
        stmts = self._state_defs(outs, n)
        # test closure reads enclosing locals directly
        (test_fn,) = _parse_stmts(f"def _pt_test_{n}():\n    return 0\n")
        test_fn.body = [ast.Return(value=_convert_test(node.test))]
        stmts.append(test_fn)
        stmts.append(self._body_fn(f"_pt_body_{n}", outs, node.body))
        names_lit = repr(tuple(outs))
        (call,) = _parse_stmts(
            f"_jst.convert_while(_pt_test_{n}, _pt_body_{n}, "
            f"_pt_get_{n}, _pt_set_{n}, {names_lit})"
        )
        return [ast.copy_location(s, node) for s in stmts] + [ast.copy_location(call, node)]

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or _blocks_transform(node.body):
            return node
        # only `for <Name> in range(...)` is rewritten; other iterables keep
        # python semantics (tensors iterate over a static leading dim)
        if not (isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and not node.iter.keywords
                and 1 <= len(node.iter.args) <= 3
                and not any(isinstance(a, ast.Starred) for a in node.iter.args)):
            return node
        body_outs = self._outs(node.body, exclude=(node.target.id,))
        if body_outs is None:
            return node
        # target leads the state so it survives the loop (python leaves the
        # loop variable bound to its final value)
        outs = [node.target.id] + body_outs
        n = self._n()
        self.changed = True
        stmts = self._state_defs(outs, n)
        body = _parse_stmts(f"{node.target.id} = _pt_idx_{n}") + node.body
        stmts.append(self._body_fn(f"_pt_body_{n}", outs, body,
                                   params=f"_pt_idx_{n}"))
        names_lit = repr(tuple(outs))
        (call,) = _parse_stmts(
            f"_jst.convert_for_range((), _pt_body_{n}, "
            f"_pt_get_{n}, _pt_set_{n}, {names_lit})"
        )
        call.value.args[0] = ast.Tuple(elts=list(node.iter.args), ctx=ast.Load())
        return [ast.copy_location(s, node) for s in stmts] + [ast.copy_location(call, node)]


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def _has_nonlocal(tree):
    return any(isinstance(n, (ast.Nonlocal, ast.Global)) for n in ast.walk(tree))


def convert_to_static(fn):
    """Rewrite ``fn`` so Python control flow over tensors lowers to lax.

    Returns ``fn`` unchanged when the source is unavailable, nothing needed
    rewriting, or the function uses features outside the transform contract
    (``nonlocal``/``global``, lambda)."""
    if not _enabled:
        return fn
    raw = fn
    if isinstance(fn, types.MethodType):
        raw = fn.__func__
    if getattr(raw, "_not_to_static", False) or getattr(raw, "_pt_converted", False):
        return fn
    if getattr(raw, "__name__", "<lambda>") == "<lambda>":
        return fn
    if hasattr(raw, "__wrapped__"):
        # `raw` is a decorator wrapper (functools.wraps): inspect.getsource
        # follows __wrapped__ to the INNER def, so recompiling here would
        # silently drop the wrapping decorator's behavior — keep python
        # semantics instead
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    if not tree.body or not isinstance(tree.body[0], (ast.FunctionDef,
                                                      ast.AsyncFunctionDef)):
        return fn
    fdef = tree.body[0]
    fdef.decorator_list = []
    if _has_nonlocal(fdef):
        return fn

    tr = ControlFlowTransformer()
    tr.visit(fdef)
    if not tr.changed:
        return fn
    ast.fix_missing_locations(tree)

    freevars = raw.__code__.co_freevars
    outer_name = "_pt_outer"
    outer = ast.parse(
        f"def {outer_name}({', '.join(freevars)}):\n    return None\n"
    ).body[0]
    outer.body = [fdef, ast.Return(value=ast.Name(id=fdef.name, ctx=ast.Load()))]
    mod = ast.Module(body=[outer], type_ignores=[])
    ast.fix_missing_locations(mod)

    g = dict(raw.__globals__)
    import paddle_tpu.jit.dy2static as _jst_mod

    g["_jst"] = _jst_mod
    code = compile(mod, filename=f"<dy2static {raw.__name__}>", mode="exec")
    ns = {}
    exec(code, g, ns)
    new_fn = ns[outer_name](*([None] * len(freevars)))
    if new_fn.__code__.co_freevars:
        # share the ORIGINAL closure cells (matched by name — the rewritten
        # code may reference a subset, possibly reordered) instead of
        # snapshotting values: live rebinding of enclosing locals keeps
        # working, and a not-yet-filled cell (recursive `@to_static def f`)
        # resolves once the decorator returns
        cells = tuple(
            raw.__closure__[raw.__code__.co_freevars.index(name)]
            for name in new_fn.__code__.co_freevars
        )
        new_fn = types.FunctionType(
            new_fn.__code__, g, raw.__name__, raw.__defaults__, cells)
    new_fn.__defaults__ = raw.__defaults__
    new_fn.__kwdefaults__ = raw.__kwdefaults__
    functools.update_wrapper(new_fn, raw, updated=())
    new_fn._pt_converted = True
    new_fn._pt_original = raw
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn
