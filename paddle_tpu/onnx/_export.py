"""Static-Program -> ONNX emitter (reference ``python/paddle/onnx/export.py``
via paddle2onnx; round-5 VERDICT missing #4).

TPU-native pipeline: trace the layer's forward into a static Program
(``static/program.py`` op tape — the same IR the Executor replays), map
each tape op to its ONNX operator, fold parameters into graph
initializers, and serialize a ModelProto through the hand-rolled protobuf
codec (``_proto.py``; the ``onnx`` package cannot be installed offline).

Covered op set = the vision model zoo's inference graphs (LeNet, the
ResNet/VGG/AlexNet families): Conv, BatchNormalization, Relu, Sigmoid,
Softmax, MaxPool, AveragePool, GlobalAveragePool, Flatten, Gemm/MatMul,
Add, Mul, Concat, Reshape, Transpose, Dropout(eval)=Identity, ReduceMean.
Unmapped tape ops raise with the op name (never a silent partial file).
"""
from __future__ import annotations

import numpy as np

from . import _proto as P

# ONNX TensorProto.DataType
_F32, _I32, _I64 = 1, 6, 7
# AttributeProto.AttributeType
_AT_FLOAT, _AT_INT, _AT_INTS = 1, 2, 7

_OPSET = 13


def _attr(name, kind, value):
    body = P.emit_bytes(1, name)
    if kind == _AT_FLOAT:
        import struct

        body += P._tag(2, P._I32) + struct.pack("<f", float(value))
    elif kind == _AT_INT:
        body += P.emit_int(3, value)
    elif kind == _AT_INTS:
        for v in value:
            body += P.emit_int(8, v)
    body += P.emit_int(20, kind)
    return body


def _node(op_type, inputs, outputs, name="", attrs=()):
    body = b"".join(P.emit_bytes(1, i) for i in inputs)
    body += b"".join(P.emit_bytes(2, o) for o in outputs)
    if name:
        body += P.emit_bytes(3, name)
    body += P.emit_bytes(4, op_type)
    for a in attrs:
        body += P.emit_msg(5, a)
    return body


def _tensor(name, arr):
    arr = np.asarray(arr)
    if arr.dtype == np.int64:
        dtype, raw = _I64, arr.astype("<i8").tobytes()
    elif arr.dtype == np.int32:
        # keep int32 as elem type 6 / <i4 raw data (upcasting to INT64
        # would silently change the graph's declared initializer types)
        dtype, raw = _I32, arr.astype("<i4").tobytes()
    else:
        dtype, raw = _F32, arr.astype("<f4").tobytes()
    body = b"".join(P.emit_int(1, d) for d in arr.shape)
    body += P.emit_int(2, dtype)
    body += P.emit_bytes(8, name)
    body += P.emit_bytes(9, raw)
    return body


def _value_info(name, shape, elem=_F32):
    dims = b""
    for d in shape:
        if d is None or (isinstance(d, int) and d < 0):
            dims += P.emit_msg(1, P.emit_bytes(2, "batch"))
        else:
            dims += P.emit_msg(1, P.emit_int(1, int(d)))
    tensor_type = P.emit_int(1, elem) + P.emit_msg(2, dims)
    return P.emit_bytes(1, name) + P.emit_msg(2, P.emit_msg(1, tensor_type))


def _pads(padding):
    """tape per-dim (begin, end) pairs -> ONNX [b0, b1, ..., e0, e1, ...]."""
    if isinstance(padding, str):
        raise NotImplementedError(
            f"onnx export: string padding {padding!r} ('same'/'valid') is "
            f"not mapped — build the layer with explicit numeric padding")
    begins = [int(p[0]) for p in padding]
    ends = [int(p[1]) for p in padding]
    return begins + ends


class _Emitter:
    def __init__(self):
        self.nodes = []
        self.initializers = {}
        self._n = 0

    def name(self, base):
        self._n += 1
        return f"{base}_{self._n}"

    def init(self, arr, base="const"):
        name = self.name(base)
        self.initializers[name] = np.asarray(arr)
        return name

    def add(self, op_type, inputs, outputs, attrs=()):
        self.nodes.append(
            _node(op_type, inputs, outputs, self.name(op_type.lower()),
                  attrs))


def _in_names(emitter, node):
    """Tape-arg names: Variables keep their tape name; Parameters/Tensors
    become initializers (deduped by id)."""
    from ..framework.tensor import Tensor
    from ..static.program import Variable

    names = []
    for a, aname in zip(node.args, node.arg_names):
        if isinstance(a, Variable):
            names.append(aname)
        elif isinstance(a, Tensor):
            key = f"p{id(a)}"
            if key not in emitter._param_cache:
                emitter._param_cache[key] = emitter.init(
                    np.asarray(a._value), getattr(a, "name", "param"))
            names.append(emitter._param_cache[key])
        elif a is None:
            names.append("")
        else:
            names.append(emitter.init(np.asarray(a)))
    return names


def _emit_op(e, node):
    op = node.op_name
    kw = node.kwargs
    ins = _in_names(e, node)
    outs = list(node.out_names)

    if op == "conv_nd":
        if kw.get("channel_last"):
            raise NotImplementedError("onnx export: NHWC conv")
        attrs = [
            _attr("strides", _AT_INTS, [int(s) for s in kw["stride"]]),
            _attr("pads", _AT_INTS, _pads(kw["padding"])),
            _attr("dilations", _AT_INTS, [int(d) for d in kw["dilation"]]),
            _attr("group", _AT_INT, kw.get("groups", 1)),
        ]
        e.add("Conv", [i for i in ins if i], outs, attrs)
    elif op == "batch_norm_infer":
        # tape order (x, mean, var, scale, bias) -> ONNX (x, scale, B,
        # mean, var)
        x, rm, rv, w, b = ins
        e.add("BatchNormalization", [x, w, b, rm, rv], outs,
              [_attr("epsilon", _AT_FLOAT, kw.get("epsilon", 1e-5))])
    elif op in ("relu", "sigmoid", "tanh", "exp", "sqrt", "abs", "neg"):
        e.add({"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "exp": "Exp", "sqrt": "Sqrt", "abs": "Abs",
               "neg": "Neg"}[op], ins, outs)
    elif op == "softmax":
        e.add("Softmax", ins, outs,
              [_attr("axis", _AT_INT, kw.get("axis", -1))])
    elif op in ("max_pool_nd", "avg_pool_nd"):
        if kw.get("channel_last"):
            raise NotImplementedError("onnx export: NHWC pool")
        attrs = [
            _attr("kernel_shape", _AT_INTS, [int(k) for k in kw["ksize"]]),
            _attr("strides", _AT_INTS, [int(s) for s in kw["stride"]]),
            _attr("pads", _AT_INTS, _pads(kw["padding"])),
        ]
        if kw.get("ceil_mode"):
            attrs.append(_attr("ceil_mode", _AT_INT, 1))
        if op == "avg_pool_nd" and not kw.get("exclusive", True):
            # paddle exclusive=False divides by the FULL window incl. pads
            attrs.append(_attr("count_include_pad", _AT_INT, 1))
        e.add("MaxPool" if op == "max_pool_nd" else "AveragePool",
              ins, outs, attrs)
    elif op == "adaptive_avg_pool_nd":
        osize = kw.get("output_size")
        osz = (osize if isinstance(osize, (tuple, list)) else (osize,))
        if any(int(s) != 1 for s in osz):
            raise NotImplementedError(
                "onnx export: adaptive pool with output_size != 1")
        e.add("GlobalAveragePool", ins, outs)
    elif op == "flatten":
        start = kw.get("start_axis", 0)
        stop = kw.get("stop_axis", -1)
        if start == 1 and stop == -1:
            # exact ONNX Flatten semantics (output [d0, prod(rest)])
            e.add("Flatten", ins, outs, [_attr("axis", _AT_INT, 1)])
        elif start >= 1:
            # general paddle flatten keeps dims < start: emit Reshape to
            # the traced output shape with dim0 symbolic (batch)
            out_shape = [-1] + [int(d) for d in node.outs[0].shape[1:]]
            shape = e.init(np.asarray(out_shape, np.int64), "shape")
            e.add("Reshape", [ins[0], shape], outs)
        else:
            raise NotImplementedError(
                "onnx export: flatten(start_axis=0) folds the batch dim "
                "and cannot stay batch-polymorphic")
    elif op == "linear":
        x, w, b = (ins + [""])[:3]
        x_rank = len(node.args[0].shape) if hasattr(node.args[0], "shape") \
            else 2
        if x_rank == 2:
            # paddle weight is [in, out]: Gemm(transB=0) consumes it as-is
            e.add("Gemm", [x, w] + ([b] if b else []), outs,
                  [_attr("alpha", _AT_FLOAT, 1.0),
                   _attr("beta", _AT_FLOAT, 1.0),
                   _attr("transB", _AT_INT, 0)])
        else:
            # ONNX Gemm is rank-2 only: higher-rank inputs broadcast
            # through MatMul (+ Add for the bias)
            if b:
                mm = e.name("matmul_out")
                e.add("MatMul", [x, w], [mm])
                e.add("Add", [mm, b], outs)
            else:
                e.add("MatMul", [x, w], outs)
    elif op == "matmul":
        if kw.get("transpose_x") or kw.get("transpose_y"):
            raise NotImplementedError("onnx export: transposed matmul")
        e.add("MatMul", ins, outs)
    elif op in ("add", "elementwise_add"):
        e.add("Add", ins, outs)
    elif op in ("multiply", "elementwise_mul"):
        e.add("Mul", ins, outs)
    elif op in ("subtract", "elementwise_sub"):
        e.add("Sub", ins, outs)
    elif op == "concat":
        e.add("Concat", ins, outs,
              [_attr("axis", _AT_INT, kw.get("axis", 0))])
    elif op == "reshape":
        shape = e.init(np.asarray(kw["shape"], np.int64), "shape")
        e.add("Reshape", [ins[0], shape], outs)
    elif op == "transpose":
        e.add("Transpose", ins, outs,
              [_attr("perm", _AT_INTS, [int(p) for p in kw["perm"]])])
    elif op == "mean":
        axis = kw.get("axis")
        attrs = [_attr("keepdims", _AT_INT,
                       1 if kw.get("keepdim") else 0)]
        if axis is not None:
            ax = axis if isinstance(axis, (tuple, list)) else [axis]
            attrs.append(_attr("axes", _AT_INTS, [int(a) for a in ax]))
        e.add("ReduceMean", ins, outs, attrs)
    elif op == "dropout":
        # eval-mode tape: identity
        e.add("Identity", ins[:1], outs)
    else:
        raise NotImplementedError(
            f"onnx export: tape op {op!r} has no ONNX mapping (covered set "
            f"targets the vision model zoo; use format_='stablehlo' for "
            f"arbitrary programs)")


def export_program(program, inputs, outputs, path, producer="paddle_tpu"):
    """Emit `program`'s tape as ``<path>.onnx``; returns the file path."""
    e = _Emitter()
    e._param_cache = {}
    for node in program.ops:
        _emit_op(e, node)

    def _elem(v):
        dt = str(getattr(v, "dtype", "float32"))
        if "int64" in dt:
            return _I64
        if "int32" in dt:
            return 6
        if "bool" in dt:
            return 9
        return _F32

    graph = b"".join(P.emit_msg(1, n) for n in e.nodes)
    graph += P.emit_bytes(2, "paddle_tpu_graph")
    for name, arr in e.initializers.items():
        graph += P.emit_msg(5, _tensor(name, arr))
    for v in inputs:
        shape = [None] + list(v.shape)[1:]  # dim0 exported symbolic
        graph += P.emit_msg(11, _value_info(v.name, shape, _elem(v)))
    for v in outputs:
        graph += P.emit_msg(12, _value_info(
            v.name, [None] + list(v.shape)[1:], _elem(v)))

    opset = P.emit_bytes(1, "") + P.emit_int(2, _OPSET)
    model = (P.emit_int(1, 8)                      # ir_version
             + P.emit_bytes(2, producer)
             + P.emit_bytes(3, "0.0")
             + P.emit_msg(7, graph)
             + P.emit_msg(8, opset))

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
