"""paddle.onnx (reference ``python/paddle/onnx/export.py`` — paddle2onnx).

Round-5: ``export`` now PRODUCES the named ``.onnx`` artifact for the
vision-zoo op set via the in-tree static-Program -> ONNX emitter
(``export.py`` + the hand-rolled protobuf codec ``_proto.py`` — the
``onnx``/``paddle2onnx`` packages are not installable offline). Programs
whose tape uses ops outside the covered set raise with the op name;
``format_="stablehlo"`` remains the fully-general portable artifact
(``paddle.jit.save`` format, ingestible by MLIR toolchains).
"""
from __future__ import annotations

__all__ = ["export", "load_structure"]


def export(layer, path, input_spec=None, opset_version=13, *,
           format_="onnx", **configs):
    """Export ``layer``.

    ``format_="onnx"`` (default, reference signature): traces the layer
    into a static Program and emits ``<path>.onnx`` (ModelProto, opset
    13). Covered ops = the vision model zoo's inference graphs; anything
    else raises NotImplementedError naming the op.

    ``format_="stablehlo"``: writes StableHLO + weights at ``path``
    (``.pdmodel``/``.pdiparams``, loadable by ``paddle.jit.load`` and any
    MLIR toolchain) and returns the path.
    """
    if format_ == "stablehlo":
        from ..jit.save_load import save as jit_save

        jit_save(layer, path, input_spec=input_spec)
        return path
    if format_ != "onnx":
        raise ValueError(f"unknown export format {format_!r}")
    if int(opset_version) != 13:
        # no silently-ignored knob: the emitter's op mappings are written
        # and tested against opset 13 semantics (Softmax axis, ceil_mode)
        raise ValueError(
            f"paddle.onnx.export emits opset 13; opset_version="
            f"{opset_version} is not supported")

    from .. import static
    from ._export import export_program

    if input_spec is None:
        raise ValueError(
            "paddle.onnx.export requires input_spec (list of InputSpec or "
            "example Tensors) to trace the forward")

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        main = static.Program()
        with static.program_guard(main):
            ins = []
            for i, spec in enumerate(input_spec):
                shape = list(spec.shape)
                if shape and (shape[0] is None or shape[0] == -1):
                    shape[0] = 1  # trace at batch 1; exported dim0 symbolic
                dtype = getattr(spec, "dtype", "float32")
                ins.append(static.data(f"input_{i}", shape, str(dtype)))
            out = layer(*ins)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return export_program(main, ins, outs, path)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()


def load_structure(path):
    """Parse a file produced by :func:`export` (via
    ``_export.export_program``) back into a structural summary (node
    op_types/io, initializer names+arrays, graph inputs/outputs) —
    inspection/testing aid; execution stays with the StableHLO artifact.
    Initializer element types FLOAT (1), INT32 (6) and INT64 (7) are
    decoded; anything else raises rather than misreading raw bytes."""
    import numpy as np

    from . import _proto as P

    with open(path, "rb") as f:
        model = P.parse(f.read())
    graph = P.parse(model[7][0])
    nodes = []
    for raw in graph.get(1, []):
        n = P.parse(raw)
        nodes.append({
            "op_type": n[4][0].decode(),
            "inputs": [s.decode() for s in n.get(1, [])],
            "outputs": [s.decode() for s in n.get(2, [])],
        })
    inits = {}
    _elem_np = {1: "<f4", 6: "<i4", 7: "<i8"}
    for raw in graph.get(5, []):
        t = P.parse(raw)
        name = t[8][0].decode()
        dims = tuple(t.get(1, []))
        dt = t[2][0]
        raw_data = t.get(9, [b""])[0]
        if dt not in _elem_np:
            raise NotImplementedError(
                f"load_structure: initializer {name!r} has ONNX elem type "
                f"{dt}, outside the emitted set (FLOAT/INT32/INT64)")
        arr = np.frombuffer(raw_data, dtype=_elem_np[dt]).reshape(dims)
        inits[name] = arr

    def _names(field):
        return [P.parse(v)[1][0].decode() for v in graph.get(field, [])]

    return {
        "ir_version": model[1][0],
        "opset": P.parse(model[8][0])[2][0],
        "producer": model[2][0].decode(),
        "nodes": nodes,
        "initializers": inits,
        "inputs": _names(11),
        "outputs": _names(12),
    }
