"""paddle.onnx (reference ``python/paddle/onnx/export.py`` — paddle2onnx).

TPU-native export story: the portable artifact is StableHLO via
``paddle.jit.save`` (jit/save_load.py), which MLIR-consuming toolchains
ingest directly. ``export`` performs that export at the requested path; an
actual ``.onnx`` conversion additionally requires the optional
``paddle2onnx``/``onnx`` packages (not present in this environment), and
raises a clear error for that step only.
"""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Exports ``layer`` as StableHLO + weights at ``path`` (always), then
    attempts the ONNX conversion when the onnx package is available."""
    from ..jit.save_load import save as jit_save

    jit_save(layer, path, input_spec=input_spec)
    try:
        import onnx  # noqa: F401

        detail = ("the StableHLO->ONNX conversion step is not wired yet")
    except ImportError:
        detail = "onnx is not installed"
    warnings.warn(
        f"exported StableHLO + weights at {path!r} (.pdmodel/.pdiparams); "
        f"no .onnx file was written ({detail})", stacklevel=2)
    return path
