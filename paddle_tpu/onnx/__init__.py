"""paddle.onnx (reference ``python/paddle/onnx/export.py`` — paddle2onnx).

TPU-native export story: the portable artifact is StableHLO via
``paddle.jit.save`` (jit/save_load.py), which MLIR-consuming toolchains
ingest directly.  An actual ``.onnx`` conversion requires the
``paddle2onnx``/``onnx`` packages, which are not available in this
offline environment — so ``export`` RAISES for the default onnx format
(never a silent warning that leaves the named artifact unwritten) and
performs the StableHLO export only on explicit opt-in
(``format_="stablehlo"``).
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, *,
           format_="onnx", **configs):
    """Export ``layer``.

    ``format_="stablehlo"``: writes StableHLO + weights at ``path``
    (``.pdmodel``/``.pdiparams``, loadable by ``paddle.jit.load`` and any
    MLIR toolchain) and returns the path.

    ``format_="onnx"`` (default, reference signature): requires the
    ``onnx`` package for the conversion step; unavailable here, so this
    raises rather than pretending the ``.onnx`` artifact exists.
    """
    if format_ == "stablehlo":
        from ..jit.save_load import save as jit_save

        jit_save(layer, path, input_spec=input_spec)
        return path
    if format_ != "onnx":
        raise ValueError(f"unknown export format {format_!r}")
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle.onnx.export cannot produce a .onnx file: the 'onnx' "
            "package is not installed in this environment. Use "
            "export(..., format_='stablehlo') for the portable StableHLO "
            "artifact (paddle.jit.save format), or install onnx/paddle2onnx."
        ) from None
    raise RuntimeError(
        "paddle.onnx.export: the StableHLO->ONNX conversion step is not "
        "implemented; use export(..., format_='stablehlo') for the portable "
        "StableHLO artifact instead"
    )
