"""Minimal protobuf wire-format codec for the ONNX schema subset the
exporter emits (``onnx/onnx.proto`` field numbers; the ``onnx`` package is
not installable in this offline environment, and the wire format is a
stable public spec: varint tags, length-delimited submessages).

Writer: nested dict/list structures -> bytes. Reader: bytes -> the same
structures (used by the tests to round-trip and by ``load`` for
inspection). Only the field kinds the exporter uses are implemented:
varint int, float (fixed32 via packed floats list), string/bytes,
repeated submessage.
"""
from __future__ import annotations

import struct

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement 64-bit (negative enums/ints)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def emit_int(field, value):
    return _tag(field, _VARINT) + _varint(int(value))


def emit_bytes(field, value):
    if isinstance(value, str):
        value = value.encode()
    return _tag(field, _LEN) + _varint(len(value)) + value


def emit_msg(field, payload: bytes):
    return _tag(field, _LEN) + _varint(len(payload)) + payload


def emit_packed_floats(field, values):
    body = b"".join(struct.pack("<f", float(v)) for v in values)
    return _tag(field, _LEN) + _varint(len(body)) + body


def emit_packed_ints(field, values):
    body = b"".join(_varint(int(v)) for v in values)
    return _tag(field, _LEN) + _varint(len(body)) + body


# -- reader ------------------------------------------------------------------

def _read_varint(buf, i):
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def parse(buf):
    """Decode one message level into {field: [raw values]} — varints as
    ints, LEN fields as bytes (caller recurses with `parse` where a
    submessage is expected)."""
    out = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            v, i = _read_varint(buf, i)
        elif wire == _LEN:
            ln, i = _read_varint(buf, i)
            v = bytes(buf[i:i + ln])
            i += ln
        elif wire == _I32:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == _I64:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def unpack_floats(raw: bytes):
    return list(struct.unpack(f"<{len(raw) // 4}f", raw))
