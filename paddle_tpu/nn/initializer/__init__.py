"""Parameter initializers (reference ``python/paddle/nn/initializer/`` and
``python/paddle/fluid/initializer.py``). Pure functions of the global RNG key."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as rnd
from ...framework.tensor import Tensor

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Dirac",
    "Orthogonal",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    shape = list(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv weight [out_c, in_c, *k] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return jax.random.normal(rnd.next_key(), shape, dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        z = jax.random.truncated_normal(rnd.next_key(), self.a, self.b, shape, dtype)
        return z * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(rnd.next_key(), shape, dtype, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(rnd.next_key(), shape, dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rnd.next_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(rnd.next_key(), shape, dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(rnd.next_key(), shape, dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        return v.reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        per = oc // self.groups
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(per, ic)):
                out[(g * per + i, i, *centers)] = 1.0
        return jnp.asarray(out, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.random.orthogonal(
            rnd.next_key(), shape[0], (int(np.prod(shape[1:])),)
        ).reshape(shape).astype(dtype) * self.gain


def _apply_initializer(init, shape, dtype, is_bias=False):
    """Resolve a (possibly None) initializer to a concrete jnp array."""
    if init is None:
        init = Constant(0.0) if is_bias else XavierNormal()
    if isinstance(init, Tensor) or isinstance(init, (np.ndarray, list)):
        init = Assign(init)
    return init(tuple(int(s) for s in shape), dtype)
