"""Layer — the module system.

Reference: ``python/paddle/fluid/dygraph/layers.py:84`` ``class Layer``
(parameters/buffers/sublayers registries, hooks, state_dict, train/eval).
TPU-native difference: parameters hold jax arrays; the whole tree is
pytree-flattenable (paddle_tpu.jit) so a Layer can be captured into a single
compiled XLA train step without touching user code.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework.tensor import Parameter, Tensor
from ..initializer import _apply_initializer, Constant

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """reference ``python/paddle/fluid/param_attr.py``."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        # an Initializer instance
        return ParamAttr(initializer=attr)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


_layer_name_counters = {}


def _unique_layer_name(prefix):
    n = _layer_name_counters.get(prefix, 0)
    _layer_name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._full_name = _unique_layer_name(
            name_scope or self.__class__.__name__.lower()
        )
        self._dtype = dtypes.convert_dtype(dtype)
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0

    # -- construction helpers ------------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None and is_bias:
            init = Constant(0.0)
        value = _apply_initializer(init, shape, dtype, is_bias=is_bias)
        p = Parameter(value, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        t = Tensor(jnp.zeros([], dtypes.convert_dtype(dtype) or self._dtype))
        t.persistable = bool(persistable)
        return t

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self.__dict__.pop(name, None)  # buffer lookups must route via __getattr__
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)
        return tensor

    # -- attribute routing (reference layers.py __setattr__) -----------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    object.__setattr__(self, name, None)
                    return
                if isinstance(value, Tensor):
                    params[name].set_value(value)
                    return
                params.pop(name)
            if layers is not None and name in layers and not isinstance(value, Layer):
                layers.pop(name)
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
                buffers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub in [("", self)] + (
            list(self._named_sublayers_recursive(prefix)) if include_sublayers else []
        ):
            for pname, p in sub._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{name}.{pname}" if name else (f"{prefix}.{pname}" if prefix else pname)
                yield full, p

    def _named_sublayers_recursive(self, prefix=""):
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            full = f"{prefix}.{name}" if prefix else name
            yield full, sub
            yield from sub._named_sublayers_recursive(full)

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        yield from self._named_sublayers_recursive(prefix)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = [(prefix, self)]
        if include_sublayers:
            layers += list(self._named_sublayers_recursive(prefix))
        for name, sub in layers:
            for bname, b in sub._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    # -- modes ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            # skip non-persistable buffers (reference layers.py state_dict)
            parts = name.rsplit(".", 1)
            owner = self
            if len(parts) == 2:
                for seg in parts[0].split("."):
                    owner = owner._sub_layers.get(seg, owner)
                bname = parts[1]
            else:
                bname = name
            if isinstance(owner, Layer) and bname in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(val.shape) != tuple(tgt._value.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: {val.shape} vs {tgt._value.shape}"
                    )
                tgt._value = val.astype(tgt._value.dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device moves ------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtypes.convert_dtype(dtype))
        return self

    def _to_dtype(self, dt):
        for p in self.parameters():
            if dtypes.is_floating(p.dtype):
                p._value = p._value.astype(dt)
        for b in self.buffers():
            if b is not None and dtypes.is_floating(b.dtype):
                b._value = b._value.astype(dt)
        for l in self.sublayers(include_self=True):
            l._dtype = dt

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)
