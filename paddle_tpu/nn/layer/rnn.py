"""Recurrent layers: SimpleRNN/LSTM/GRU (+ cells, RNN/BiRNN wrappers).

Reference: ``python/paddle/nn/layer/rnn.py:1`` (SimpleRNNCell:270,
LSTMCell:406, GRUCell:563, RNN:714, BiRNN:789, RNNBase:868). Cell equations
match the reference exactly (LSTM gate order i,f,g,o; GRU
``h = (h_prev - c) * z + c`` with reset applied after the h-matmul).

TPU-native design: the time loop is ONE ``lax.scan`` op per (layer,
direction) — compiled to a single fused XLA while-loop on the device rather
than the reference's per-timestep op dispatch (or cudnn descriptor calls).
``sequence_length`` masking gates state updates inside the scan, so padded
steps pass state through and emit zeros, in both directions.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, op
from ..initializer import Uniform
from .layers import Layer, ParamAttr
from .container import LayerList

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _act(name):
    return {"tanh": jnp.tanh, "relu": jax.nn.relu}[name]


# ---------------------------------------------------------------------------
# single-step cell math (shared by the cells' forward and the fused scan)
# ---------------------------------------------------------------------------

def _simple_step(x_t, h, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    g = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    h = _act(activation)(g)
    return h, (h,)


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c = f * c + i * jnp.tanh(g)
    h = o * jnp.tanh(c)
    return h, (h, c)


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    xg = x_t @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(x_r + h_r)
    z = jax.nn.sigmoid(x_z + h_z)
    c = jnp.tanh(x_c + r * h_c)   # reset gate applied after the matmul
    h = (h - c) * z + c
    return h, (h,)


@op("rnn_scan")
def _rnn_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_len=None, mode="RNN_TANH",
              reverse=False, time_major=False):
    """One (layer, direction) recurrent sweep as a single lax.scan.

    x: [B, T, I] (or [T, B, I] when time_major). h0/c0: [B, H] (c0 only for
    LSTM). seq_len: optional [B] int lengths — padded steps pass state
    through and write zero outputs. Returns (outputs, h_n[, c_n]).
    """
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)          # -> [T, B, I]
    T = x.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x = jnp.flip(x, axis=0)
        ts = jnp.flip(ts, axis=0)

    lstm = mode == "LSTM"

    def step(carry, inp):
        t, x_t = inp
        if lstm:
            h, c = carry
            out, (h_new, c_new) = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
        elif mode == "GRU":
            (h,) = carry
            out, (h_new,) = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
        else:
            (h,) = carry
            act = "relu" if mode == "RNN_RELU" else "tanh"
            out, (h_new,) = _simple_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act)
        if seq_len is not None:
            valid = (t < seq_len)[:, None]
            out = jnp.where(valid, out, 0.0)
            h_new = jnp.where(valid, h_new, carry[0])
            if lstm:
                c_new = jnp.where(valid, c_new, carry[1])
        new_carry = (h_new, c_new) if lstm else (h_new,)
        return new_carry, out

    init = (h0, c0) if lstm else (h0,)
    final, outs = lax.scan(step, init, (ts, x))
    if reverse:
        outs = jnp.flip(outs, axis=0)
    if not time_major:
        outs = jnp.swapaxes(outs, 0, 1)
    if lstm:
        return outs, final[0], final[1]
    return outs, final[0]


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

class RNNCellBase(Layer):
    """Reference ``rnn.py:143``."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ... import ops

        batch = batch_ref.shape[batch_dim_idx]
        shapes = shape or self.state_shape
        if isinstance(shapes[0], (tuple, list)):
            return tuple(
                ops.full([batch] + list(s), init_value,
                         dtype or "float32") for s in shapes
            )
        return ops.full([batch] + list(shapes), init_value, dtype or "float32")


class _GateCell(RNNCellBase):
    """Shared parameter layout: weight_ih [G*H, I], weight_hh [G*H, H]."""

    GATES = 1
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = ParamAttr(initializer=Uniform(-std, std))
        g = self.GATES
        self.weight_ih = self.create_parameter(
            [g * hidden_size, input_size], attr=weight_ih_attr or init)
        self.weight_hh = self.create_parameter(
            [g * hidden_size, hidden_size], attr=weight_hh_attr or init)
        self.bias_ih = self.create_parameter(
            [g * hidden_size], attr=bias_ih_attr or init, is_bias=True)
        self.bias_hh = self.create_parameter(
            [g * hidden_size], attr=bias_hh_attr or init, is_bias=True)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class SimpleRNNCell(_GateCell):
    """Reference ``rnn.py:270``: h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    GATES = 1

    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, **kw)
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation

    @property
    def MODE(self):
        return "RNN_RELU" if self.activation == "relu" else "RNN_TANH"

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fwd(x_t, h, w_ih, w_hh, b_ih, b_hh):
            out, (h2,) = _simple_step(x_t, h, w_ih, w_hh, b_ih, b_hh,
                                      self.activation)
            return out, h2

        out, h = apply_op("simple_rnn_cell", fwd,
                          (inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), {})
        return out, h


class LSTMCell(_GateCell):
    """Reference ``rnn.py:406``: gates i,f,g,o."""

    GATES = 4
    MODE = "LSTM"

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def fwd(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
            out, (h2, c2) = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
            return out, h2, c2

        out, h2, c2 = apply_op("lstm_cell", fwd,
                               (inputs, h, c, self.weight_ih, self.weight_hh,
                                self.bias_ih, self.bias_hh), {})
        return out, (h2, c2)


class GRUCell(_GateCell):
    """Reference ``rnn.py:563``: r,z,c with reset applied after the matmul."""

    GATES = 3
    MODE = "GRU"

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fwd(x_t, h, w_ih, w_hh, b_ih, b_hh):
            out, (h2,) = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
            return out, h2

        out, h = apply_op("gru_cell", fwd,
                          (inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh), {})
        return out, h


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------

def _run_cell_scan(cell, inputs, initial_states, sequence_length,
                   is_reverse, time_major):
    """Fused lax.scan sweep for the builtin cells."""
    lstm = cell.MODE == "LSTM"
    if initial_states is None:
        batch_idx = 1 if time_major else 0
        ref = inputs
        initial_states = cell.get_initial_states(ref, batch_dim_idx=batch_idx)
    if lstm:
        h0, c0 = initial_states
    else:
        h0 = initial_states
        if isinstance(h0, (tuple, list)):
            h0 = h0[0]
        c0 = None

    out = _rnn_scan(
        inputs, h0, c0 if lstm else None,
        cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh,
        sequence_length,
        mode=cell.MODE, reverse=bool(is_reverse), time_major=bool(time_major),
    )
    if lstm:
        outs, h_n, c_n = out
        return outs, (h_n, c_n)
    outs, h_n = out
    return outs, h_n


class RNN(Layer):
    """Reference ``rnn.py:714``: wrap a cell into a time-sweep. Builtin cells
    run as one fused scan; custom cells fall back to a python time loop."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if isinstance(self.cell, _GateCell):
            return _run_cell_scan(self.cell, inputs, initial_states,
                                  sequence_length, self.is_reverse,
                                  self.time_major)
        return self._python_loop(inputs, initial_states, sequence_length,
                                 **kwargs)

    def _python_loop(self, inputs, initial_states, sequence_length, **kwargs):
        from ... import ops

        t_axis = 0 if self.time_major else 1
        T = inputs.shape[t_axis]
        states = initial_states
        if states is None:
            states = self.cell.get_initial_states(
                inputs, batch_dim_idx=1 if self.time_major else 0)
        outs = []
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in order:
            x_t = (inputs[t] if self.time_major else inputs[:, t])
            out, new_states = self.cell(x_t, states, **kwargs)
            if sequence_length is not None:
                # same masking the fused scan applies: padded steps emit
                # zeros and pass the state through
                valid = (sequence_length > t).astype(out.dtype).unsqueeze(-1)
                out = out * valid
                if isinstance(new_states, (tuple, list)):
                    new_states = tuple(
                        ns * valid + s * (1.0 - valid)
                        for ns, s in zip(new_states, states)
                    )
                else:
                    new_states = new_states * valid + states * (1.0 - valid)
            states = new_states
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        outputs = ops.stack(outs, axis=t_axis)
        return outputs, states


class BiRNN(Layer):
    """Reference ``rnn.py:789``: forward + backward cells, concat outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major
        self._fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self._bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import ops

        st_fw = st_bw = None
        if initial_states is not None:
            st_fw, st_bw = initial_states
        out_fw, s_fw = self._fw(inputs, st_fw, sequence_length, **kwargs)
        out_bw, s_bw = self._bw(inputs, st_bw, sequence_length, **kwargs)
        outputs = ops.concat([out_fw, out_bw], axis=-1)
        return outputs, (s_fw, s_bw)


class RNNBase(Layer):
    """Reference ``rnn.py:868``: multi-layer, (bi)directional stacks."""

    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **cell_kwargs):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(
                f"direction should be forward or bidirect(ional), got {direction}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.direction = direction

        cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * self.num_directions
            for _ in range(self.num_directions):
                cells.append(self.CELL(in_sz, hidden_size, **cell_kwargs))
        self.cells = LayerList(cells)

    @property
    def _is_lstm(self):
        return self.CELL is LSTMCell

    def forward(self, inputs, initial_states=None, sequence_length=None):
        """Returns (outputs [B,T,H*D], final_states [L*D,B,H] (or tuple of
        two for LSTM))."""
        from ... import ops

        L, D = self.num_layers, self.num_directions
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]

        if initial_states is None:
            init_h = [None] * (L * D)
            init_c = [None] * (L * D)
        elif self._is_lstm:
            h_all, c_all = initial_states
            init_h = [h_all[i] for i in range(L * D)]
            init_c = [c_all[i] for i in range(L * D)]
        else:
            init_h = [initial_states[i] for i in range(L * D)]
            init_c = [None] * (L * D)

        x = inputs
        final_h, final_c = [], []
        for layer in range(L):
            outs_dir = []
            for d in range(D):
                idx = layer * D + d
                cell = self.cells[idx]
                st = None
                if init_h[idx] is not None:
                    st = ((init_h[idx], init_c[idx]) if self._is_lstm
                          else init_h[idx])
                outs, st_out = _run_cell_scan(
                    cell, x, st, sequence_length, is_reverse=(d == 1),
                    time_major=self.time_major)
                outs_dir.append(outs)
                if self._is_lstm:
                    final_h.append(st_out[0])
                    final_c.append(st_out[1])
                else:
                    final_h.append(st_out)
            x = outs_dir[0] if D == 1 else ops.concat(outs_dir, axis=-1)
            if self.dropout > 0.0 and layer < L - 1:
                from .. import functional as F

                x = F.dropout(x, self.dropout, training=self.training)

        h_n = ops.stack(final_h, axis=0)
        if self._is_lstm:
            c_n = ops.stack(final_c, axis=0)
            return x, (h_n, c_n)
        return x, h_n


class SimpleRNN(RNNBase):
    """Reference ``rnn.py:1110``."""

    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kw)


class LSTM(RNNBase):
    """Reference ``rnn.py:1221``."""

    CELL = LSTMCell


class GRU(RNNBase):
    """Reference ``rnn.py:1336``."""

    CELL = GRUCell
