"""Transformer layers.

Reference: ``python/paddle/nn/layer/transformer.py`` (MultiHeadAttention,
TransformerEncoderLayer/Encoder, TransformerDecoderLayer/Decoder,
Transformer) and the fused CUDA blocks
(``operators/fused/fused_attention_op.cu``, ``fused_feedforward_op.cu``).

TPU-native: attention runs through ``F.scaled_dot_product_attention`` (Pallas
flash-attention when available, fused-einsum XLA fallback); the "fused"
variants of the reference are unnecessary as separate modules because XLA
fuses the layernorm/residual/dropout chains. Layout is paddle's
[batch, seq, d_model].
"""
from __future__ import annotations

import collections

import numpy as np

from ...framework.tensor import Tensor
from .. import functional as F
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention",
    "TransformerEncoderLayer",
    "TransformerEncoder",
    "TransformerDecoderLayer",
    "TransformerDecoder",
    "Transformer",
]


def _convert_attention_mask(attn_mask, dtype):
    """reference transformer.py _convert_attention_mask: bool → additive."""
    if attn_mask is None:
        return None
    if str(attn_mask.dtype) == "bool":
        from ... import ops

        return ops.where(
            attn_mask,
            ops.zeros_like(attn_mask.astype(dtype)),
            ops.full_like(attn_mask.astype(dtype), -1e9),
        )
    return attn_mask


class MultiHeadAttention(Layer):
    """reference ``nn/layer/transformer.py MultiHeadAttention``."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(
        self,
        embed_dim,
        num_heads,
        dropout=0.0,
        kdim=None,
        vdim=None,
        need_weights=False,
        weight_attr=None,
        bias_attr=None,
    ):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self.q_proj(query)
        b, ql = q.shape[0], q.shape[1]
        q = q.reshape([b, ql, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key).reshape([b, -1, self.num_heads, self.head_dim])
            v = self.v_proj(value).reshape([b, -1, self.num_heads, self.head_dim])
        if isinstance(cache, self.Cache):
            from ... import ops

            k = ops.concat([cache.k, k], axis=1)
            v = ops.concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return q, k, v, cache

    def gen_cache(self, key, value=None, type=None):
        from ... import ops

        if type == MultiHeadAttention.StaticCache:
            k = self.k_proj(key).reshape([key.shape[0], -1, self.num_heads, self.head_dim])
            v = self.v_proj(value if value is not None else key).reshape(
                [key.shape[0], -1, self.num_heads, self.head_dim]
            )
            return self.StaticCache(k, v)
        b = key.shape[0]
        k = ops.zeros([b, 0, self.num_heads, self.head_dim], dtype=key.dtype)
        return self.Cache(k, ops.zeros_like(k))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        mask = _convert_attention_mask(attn_mask, q.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout if self.training else 0.0
        )
        b, ql = out.shape[0], out.shape[1]
        out = out.reshape([b, ql, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    """reference ``nn/layer/transformer.py TransformerEncoderLayer``
    (normalize_before = pre-LN vs post-LN)."""

    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        # build independent copies from the prototype's config (reference
        # uses type(encoder_layer)(*args) via _config storage)
        self.layers = LayerList(
            [encoder_layer if i == 0 else _clone_layer(encoder_layer) for i in range(num_layers)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_cache = mod(output, src_mask=src_mask, cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


class TransformerDecoderLayer(Layer):
    """reference ``nn/layer/transformer.py TransformerDecoderLayer``."""

    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        layer_norm_eps=1e-5,
    ):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr
        )
        self.cross_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout, weight_attr=weight_attr, bias_attr=bias_attr
        )
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory, type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer if i == 0 else _clone_layer(decoder_layer) for i in range(num_layers)]
        )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
            else:
                output, new_cache = mod(
                    output, memory, tgt_mask=tgt_mask, memory_mask=memory_mask, cache=cache[i]
                )
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [l.gen_cache(memory) for l in self.layers]
        if do_zip:
            caches = list(zip(*caches))
        return caches


def _clone_layer(layer):
    """Fresh re-init of a prototype layer (reference re-constructs from
    config; parameters are re-drawn, matching reference semantics where each
    stacked layer gets its own init)."""
    import copy

    new = copy.deepcopy(layer)
    # re-draw parameters so clones are independently initialized
    for (name, p_new), (_, p_old) in zip(
        new.named_parameters(), layer.named_parameters()
    ):
        import jax.numpy as jnp

        from ...framework import random as frandom
        import jax

        if p_new.ndim >= 2:
            k = frandom.next_key()
            fan_in, fan_out = p_new.shape[-2], p_new.shape[-1]
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            p_new._value = std * jax.random.normal(k, p_new._value.shape, p_new._value.dtype)
    return new


class Transformer(Layer):
    """reference ``nn/layer/transformer.py Transformer`` (full enc-dec)."""

    def __init__(
        self,
        d_model=512,
        nhead=8,
        num_encoder_layers=6,
        num_decoder_layers=6,
        dim_feedforward=2048,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
        weight_attr=None,
        bias_attr=None,
        custom_encoder=None,
        custom_decoder=None,
    ):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr,
            )
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    def generate_square_subsequent_mask(self, length):
        from ... import ops

        return ops.tril(ops.full([length, length], 0.0)) + ops.triu(
            ops.full([length, length], -np.inf), 1
        )
