"""Pooling layers (reference ``python/paddle/nn/layer/pooling.py``)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
    "MaxUnPool1D",
    "MaxUnPool2D",
    "MaxUnPool3D",
]


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, exclusive=True, data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive
        self.data_format = data_format


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, return_mask, data_format="NCL")

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode, self.data_format)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode, self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, return_mask, data_format=data_format)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding, self.return_mask, self.ceil_mode, self.data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive, data_format="NCL")

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding, self.exclusive, self.ceil_mode, self.data_format)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive, data_format=data_format)

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, None, self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, exclusive=exclusive, data_format=data_format)

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding, self.ceil_mode, self.exclusive, None, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size, self._data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size, self._return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool1d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=osz)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool2d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=osz)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return F.max_unpool3d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=osz)
