"""Norm layers (reference ``python/paddle/nn/layer/norm.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from .. import functional as F
from ..initializer import Constant, Normal
from .layers import Layer

__all__ = [
    "BatchNorm",
    "BatchNorm1D",
    "BatchNorm2D",
    "BatchNorm3D",
    "SyncBatchNorm",
    "LayerNorm",
    "GroupNorm",
    "InstanceNorm1D",
    "InstanceNorm2D",
    "InstanceNorm3D",
    "LocalResponseNorm",
    "SpectralNorm",
    "RMSNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0)
        )
        self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], self._dtype)))

    def forward(self, input):
        return F.batch_norm(
            input,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm(num_channels) (reference
    ``fluid/dygraph/nn.py BatchNorm``) — keeps act param."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32", data_layout="NCHW", in_place=False, moving_mean_name=None, moving_variance_name=None, do_model_average_for_mean_and_var=True, use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr, data_layout, use_global_stats or None)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm (reference ``nn/layer/norm.py SyncBatchNorm``,
    CUDA ``sync_batch_norm_op.cu``). Under the jit/pmap path the mean/var
    reduction happens over the mesh data axis via psum (see
    paddle_tpu.distributed); in single-device eager it equals BatchNorm."""

    @staticmethod
    def _candidate_axes():
        """Mesh axes the cross-replica reduction may ride: the fleet
        data-parallel axis when a hybrid topology is initialized, the 'dp'
        convention, and the default world group's axis."""
        axes = []
        try:
            from ...distributed import fleet

            hcg = fleet.get_hybrid_communicate_group()
            if hcg is not None:
                axes.append(hcg.get_data_parallel_group().axis_name)
        except Exception:
            pass
        axes.append("dp")
        try:
            from ...distributed.collective import _default_group

            axes.append(_default_group().axis_name)
        except Exception:
            pass
        return axes

    def forward(self, input):
        from ...distributed import collective as coll

        if self.training:
            for axis_name in self._candidate_axes():
                if coll._in_spmd(axis_name):
                    return self._spmd_forward(input, axis_name)
        return super().forward(input)

    def _spmd_forward(self, input, axis_name):
        from ...ops.dispatch import op as _op

        axis = 1
        eps = self._epsilon

        @_op("sync_batch_norm")
        def _sync_bn(x, w, b):
            axes = tuple(i for i in range(x.ndim) if i != axis)
            from jax import lax

            local_mean = jnp.mean(x, axis=axes)
            local_sq = jnp.mean(jnp.square(x), axis=axes)
            mean = lax.pmean(local_mean, axis_name)
            sq = lax.pmean(local_sq, axis_name)
            var = sq - jnp.square(mean)
            shape = [1] * x.ndim
            shape[axis] = x.shape[axis]
            scale = w.reshape(shape) * lax.rsqrt(var.reshape(shape) + eps)
            out = x * scale + (b.reshape(shape) - mean.reshape(shape) * scale)
            # running buffers store the *unbiased* variance over the global
            # batch (matching F.batch_norm), normalization uses biased
            n_g = (x.size // x.shape[axis]) * lax.axis_size(axis_name)
            var_unbiased = var * (n_g / max(n_g - 1, 1))
            return out, mean, var_unbiased

        out, mean, var = _sync_bn(input, self.weight, self.bias)
        # Running-stat update with the cross-replica batch stats, so eval
        # (which reads the buffers via super().forward) sees learned
        # population statistics. Inside a shard_map region these are traced
        # values: the enclosing functionalization (CompiledStep state
        # threading, or a shard_map body that returns the buffers) carries
        # them out — the same contract as every other mutable buffer.
        mom = self._momentum
        mv = mean._value if isinstance(mean, Tensor) else mean
        vv = var._value if isinstance(var, Tensor) else var
        self._mean._value = (
            mom * self._mean._value + (1.0 - mom) * mv.astype(self._mean._value.dtype)
        )
        self._variance._value = (
            mom * self._variance._value
            + (1.0 - mom) * vv.astype(self._variance._value.dtype)
        )
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon, data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in layer._sub_layers.items():
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """RMS norm (no reference equivalent layer; standard for LLM families)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0)
        )

    def forward(self, x):
        from ...ops.dispatch import op as _op

        eps = self._epsilon

        @_op("rms_norm")
        def _rms(xv, w):
            from jax import lax

            ms = jnp.mean(jnp.square(xv), axis=-1, keepdims=True)
            return xv * lax.rsqrt(ms + eps) * w

        return _rms(x, self.weight)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            shape=[num_channels], attr=weight_attr, default_initializer=Constant(1.0)
        )
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True
        )

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr, default_initializer=Constant(1.0)
            )
            self.bias = self.create_parameter(shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(shape=[h], default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(shape=[w], default_initializer=Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, x):
        return F.spectral_norm(x, self.weight_u, self.weight_v, self._dim, self._power_iters, self._epsilon)
