"""Pooling (reference ``python/paddle/nn/functional/pooling.py``) via
``lax.reduce_window``."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import op
from .conv import _norm_tuple, _norm_padding


def _window_dims(nd, ksize, stride, channel_last):
    if channel_last:
        return (1, *ksize, 1), (1, *stride, 1)
    return (1, 1, *ksize), (1, 1, *stride)


def _full_padding(nd, pad_spec, channel_last):
    if isinstance(pad_spec, str):
        return pad_spec
    if channel_last:
        return ((0, 0), *pad_spec, (0, 0))
    return ((0, 0), (0, 0), *pad_spec)


@op("max_pool_nd")
def _max_pool_raw(x, ksize=(), stride=(), padding="VALID", channel_last=False, nd=2, ceil_mode=False):
    wd, ws = _window_dims(nd, ksize, stride, channel_last)
    pad = _full_padding(nd, padding, channel_last)
    if isinstance(pad, str):
        return lax.reduce_window(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min, lax.max, wd, ws, pad)
    if ceil_mode:
        pad = _ceil_pad(x, wd, ws, pad)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, wd, ws, pad)


def _ceil_pad(x, wd, ws, pad):
    pad = list(pad)
    for i in range(len(pad)):
        if wd[i] == 1:
            continue
        size = x.shape[i] + pad[i][0] + pad[i][1]
        rem = (size - wd[i]) % ws[i]
        if rem:
            pad[i] = (pad[i][0], pad[i][1] + ws[i] - rem)
    return tuple(pad)


@op("avg_pool_nd")
def _avg_pool_raw(x, ksize=(), stride=(), padding="VALID", channel_last=False, nd=2, exclusive=True, ceil_mode=False):
    wd, ws = _window_dims(nd, ksize, stride, channel_last)
    pad = _full_padding(nd, padding, channel_last)
    if not isinstance(pad, str) and ceil_mode:
        pad = _ceil_pad(x, wd, ws, pad)
    summed = lax.reduce_window(x, 0.0, lax.add, wd, ws, pad)
    if exclusive and not (isinstance(pad, str) and pad == "VALID"):
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, wd, ws, pad)
        return summed / counts
    return summed / float(np.prod(wd))


def _pool(kind, x, kernel_size, stride, padding, nd, data_format, ceil_mode=False, exclusive=True):
    channel_last = data_format.endswith("C")
    ks = _norm_tuple(kernel_size, nd)
    st = _norm_tuple(stride if stride is not None else kernel_size, nd)
    pad_spec, _ = _norm_padding(padding, nd)
    if kind == "max":
        return _max_pool_raw(x, ksize=ks, stride=st, padding=pad_spec if isinstance(pad_spec, str) else tuple(pad_spec), channel_last=channel_last, nd=nd, ceil_mode=ceil_mode)
    return _avg_pool_raw(x, ksize=ks, stride=st, padding=pad_spec if isinstance(pad_spec, str) else tuple(pad_spec), channel_last=channel_last, nd=nd, exclusive=exclusive, ceil_mode=ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    out = _pool("max", x, kernel_size, stride, padding, 1, df, ceil_mode)
    if not return_mask:
        return out
    return out, _pool_indices(x, kernel_size, stride, padding, 1, df,
                              ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    out = _pool("max", x, kernel_size, stride, padding, 2, data_format, ceil_mode)
    if not return_mask:
        return out
    return out, _pool_indices(x, kernel_size, stride, padding, 2,
                              data_format, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool("max", x, kernel_size, stride, padding, 3, data_format, ceil_mode)
    if not return_mask:
        return out
    return out, _pool_indices(x, kernel_size, stride, padding, 3,
                              data_format, ceil_mode)


@op("max_pool_indices")
def _pool_indices_raw(x, ksize=(), stride=(), pads=(), nd=2,
                      channel_last=False):
    """Flat spatial argmax index per pooling window (reference return_mask
    semantics: indices address the input's flattened spatial dims, in input
    coordinates even with padding). Window elements are materialized with
    ``conv_general_dilated_patches`` and argmax'd; padded positions carry
    -inf so they are never selected."""
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    # explicit -inf pad so padded cells never win the argmax
    pad_widths = [(0, 0), (0, 0)] + [tuple(p) for p in pads]
    xp = jnp.pad(x.astype(jnp.float32), pad_widths,
                 constant_values=-jnp.inf)
    xr = xp.reshape((n * c, 1) + xp.shape[2:])
    patches = lax.conv_general_dilated_patches(
        xr, filter_shape=tuple(ksize), window_strides=tuple(stride),
        padding=[(0, 0)] * nd,
    )
    out_spatial = patches.shape[2:]
    offs = jnp.argmax(patches, axis=1)          # (n*c, *out_spatial)
    # unravel the within-window offset, map to input coords, flatten
    flat = jnp.zeros_like(offs)
    rem = offs
    strides_total = 1
    coords = []
    for d in range(nd - 1, -1, -1):
        coords.append(rem % ksize[d])
        rem = rem // ksize[d]
    coords = coords[::-1]
    for d in range(nd):
        grid = lax.broadcasted_iota(jnp.int32, offs.shape, 1 + d)
        in_coord = grid * stride[d] - pads[d][0] + coords[d]
        flat = flat * spatial[d] + in_coord
    flat = flat.reshape((n, c) + out_spatial)
    if channel_last:
        flat = jnp.moveaxis(flat, 1, -1)
    return flat.astype(jnp.int32)


def _pool_indices(x, kernel_size, stride, padding, nd, data_format,
                  ceil_mode=False):
    channel_last = data_format.endswith("C")
    ks = _norm_tuple(kernel_size, nd)
    st = _norm_tuple(stride if stride is not None else kernel_size, nd)
    pad_spec, _ = _norm_padding(padding, nd)
    if isinstance(pad_spec, str):
        pad_spec = ((0, 0),) * nd if pad_spec == "VALID" else None
    if pad_spec is None:
        raise ValueError("return_mask does not support SAME padding")
    pads = [list(p) for p in pad_spec]
    if ceil_mode:
        # mirror _ceil_pad: grow the right pad so the mask tiles like the
        # pooled output
        sdims = (list(x.shape[1:-1]) if channel_last
                 else list(x.shape[2:]))
        for d in range(nd):
            size = sdims[d] + pads[d][0] + pads[d][1]
            rem = (size - ks[d]) % st[d]
            if rem:
                pads[d][1] += st[d] - rem
    return _pool_indices_raw(
        x, ksize=ks, stride=st, pads=tuple(tuple(p) for p in pads),
        nd=nd, channel_last=channel_last)


def _unpool(x, indices, kernel_size, stride, padding, nd, data_format,
            output_size):
    """Scatter pooled values back to the argmax positions (reference
    ``max_unpool{1,2,3}d``; the CUDA kernel is a scatter over the mask)."""
    from ...ops.dispatch import apply_op

    channel_last = data_format.endswith("C")
    ks = _norm_tuple(kernel_size, nd)
    st = _norm_tuple(stride if stride is not None else kernel_size, nd)
    pd = _norm_tuple(padding, nd) if not isinstance(padding, (list, tuple))         else _norm_tuple(padding, nd)

    def fwd(v, idx):
        vv = jnp.moveaxis(v, -1, 1) if channel_last else v
        ii = jnp.moveaxis(idx, -1, 1) if channel_last else idx
        n, c = vv.shape[0], vv.shape[1]
        in_spatial = vv.shape[2:]
        if output_size is not None:
            out_spatial = tuple(int(o) for o in output_size)[-nd:]
        else:
            out_spatial = tuple(
                (in_spatial[d] - 1) * st[d] - 2 * pd[d] + ks[d]
                for d in range(nd))
        L = 1
        for o in out_spatial:
            L *= o
        flat = jnp.zeros((n, c, L), vv.dtype)
        bidx = lax.broadcasted_iota(jnp.int32, ii.shape, 0)
        cidx = lax.broadcasted_iota(jnp.int32, ii.shape, 1)
        flat = flat.at[bidx.reshape(n, c, -1),
                       cidx.reshape(n, c, -1),
                       ii.reshape(n, c, -1)].set(vv.reshape(n, c, -1))
        out = flat.reshape((n, c) + out_spatial)
        return jnp.moveaxis(out, 1, -1) if channel_last else out

    return apply_op("max_unpool%dd" % nd, fwd, (x, indices), {})


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, 1,
                   "NCW" if data_format in ("NCL", "NCW") else "NWC",
                   output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, 2, data_format,
                   output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, 3, data_format,
                   output_size)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _pool("avg", x, kernel_size, stride, padding, 1, df, ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool("avg", x, kernel_size, stride, padding, 2, data_format, ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool("avg", x, kernel_size, stride, padding, 3, data_format, ceil_mode, exclusive)


@op("adaptive_avg_pool_nd")
def _adaptive_avg_raw(x, output_size=(), channel_last=False, nd=2):
    spatial_start = 1 if channel_last else 2
    out = x
    for i, os_ in enumerate(output_size):
        axis = spatial_start + i
        in_sz = out.shape[axis]
        if in_sz % os_ == 0:
            k = in_sz // os_
            shape = list(out.shape)
            shape[axis : axis + 1] = [os_, k]
            out = jnp.mean(out.reshape(shape), axis=axis + 1)
        else:
            # general adaptive: averaging over variable windows
            starts = (np.arange(os_) * in_sz) // os_
            ends = ((np.arange(os_) + 1) * in_sz + os_ - 1) // os_
            segs = [
                jnp.mean(lax.slice_in_dim(out, int(s), int(e), axis=axis), axis=axis, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(segs, axis=axis)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    os_ = _norm_tuple(output_size, 1)
    return _adaptive_avg_raw(x, output_size=os_, channel_last=False, nd=1)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os_ = _norm_tuple(output_size, 2)
    return _adaptive_avg_raw(x, output_size=os_, channel_last=data_format.endswith("C"), nd=2)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    os_ = _norm_tuple(output_size, 3)
    return _adaptive_avg_raw(x, output_size=os_, channel_last=data_format.endswith("C"), nd=3)


@op("adaptive_max_pool_nd")
def _adaptive_max_raw(x, output_size=(), channel_last=False, nd=2):
    spatial_start = 1 if channel_last else 2
    out = x
    for i, os_ in enumerate(output_size):
        axis = spatial_start + i
        in_sz = out.shape[axis]
        if in_sz % os_ == 0:
            k = in_sz // os_
            shape = list(out.shape)
            shape[axis : axis + 1] = [os_, k]
            out = jnp.max(out.reshape(shape), axis=axis + 1)
        else:
            starts = (np.arange(os_) * in_sz) // os_
            ends = ((np.arange(os_) + 1) * in_sz + os_ - 1) // os_
            segs = [
                jnp.max(lax.slice_in_dim(out, int(s), int(e), axis=axis), axis=axis, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            out = jnp.concatenate(segs, axis=axis)
    return out


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max_raw(x, output_size=_norm_tuple(output_size, 1), channel_last=False, nd=1)
    return (out, _pool_mask(x, out)) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max_raw(x, output_size=_norm_tuple(output_size, 2), channel_last=False, nd=2)
    return (out, _pool_mask(x, out)) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max_raw(x, output_size=_norm_tuple(output_size, 3), channel_last=False, nd=3)
    return (out, _pool_mask(x, out)) if return_mask else out


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    from ...ops import math as m

    xp = m.pow_(m.abs(x), p)
    pooled = avg_pool1d(xp, kernel_size, stride, padding, exclusive=False, ceil_mode=ceil_mode, data_format=data_format)
    k = kernel_size if isinstance(kernel_size, int) else int(np.prod(kernel_size))
    return m.pow_(m.multiply(pooled, k), 1.0 / p)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    from ...ops import math as m

    xp = m.pow_(m.abs(x), p)
    pooled = avg_pool2d(xp, kernel_size, stride, padding, ceil_mode=ceil_mode, exclusive=False, data_format=data_format)
    ks = _norm_tuple(kernel_size, 2)
    return m.pow_(m.multiply(pooled, float(np.prod(ks))), 1.0 / p)
