"""Attention functionals.

Reference fused kernels: ``paddle/fluid/operators/fused/fused_attention_op.cu``
and ``fmha_ref.h``. TPU-native path: a Pallas flash-attention kernel
(``paddle_tpu.ops.pallas.flash_attention``) for long sequences, with an XLA
einsum fallback for small/odd shapes."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...ops.dispatch import op


@op("sdpa")
def _sdpa_raw(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None, use_pallas=True):
    """q,k,v: (batch, seq, heads, head_dim) — paddle layout."""
    if use_pallas:
        try:
            from ...ops.pallas.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, mask=mask, causal=causal, scale=scale)
        except Exception:
            pass
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None
):
    if attn_mask is not None:
        return _sdpa_raw(query, key, value, attn_mask, dropout_p=dropout_p, causal=is_causal, use_pallas=False)
    return _sdpa_raw(query, key, value, dropout_p=dropout_p, causal=is_causal)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False, fixed_seed_offset=None, rng_name="", training=True, name=None):
    out = _sdpa_raw(query, key, value, dropout_p=dropout, causal=causal)
    if return_softmax:
        return out, None
    return out, None
