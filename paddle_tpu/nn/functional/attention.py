"""Attention functionals.

Reference fused kernels: ``paddle/fluid/operators/fused/fused_attention_op.cu``
and ``fmha_ref.h``. TPU-native path: the Pallas flash-attention kernel
(``paddle_tpu.ops.pallas.flash_attention``) whenever shapes tile onto the MXU
and no attention dropout is requested; an XLA einsum path otherwise.

Long context adds a third path: a blockwise online-softmax ``lax.scan`` over
KV blocks (``_sdpa_blockwise``) that keeps the live logits at
O(seq·block) instead of O(seq²) on every backend, selected for causal
training above ``blockwise_attention_min_kv`` keys and for every cached
(:class:`LengthMask`) serving call — prefill, chunked prefill, decode and
speculative verify never materialize ``[b, h, q, max_len]`` scores.

Routing is an EXPLICIT capability check (``_flash_ok`` /
``_blockwise_ok``), never a silent ``except`` fallback: if a kernel is
selected and fails, the error propagates.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as rnd
from ...ops.dispatch import op

#: additive-mask floor shared with serving.kv_cache.MASK_MIN
NEG_INF = -1e30


class LengthMask:
    """Compact validity descriptor for cached (length-masked) attention.

    Key slot ``j`` attends to query row ``i`` of batch ``b`` iff
    ``j <= q_pos[b, i]`` and, when ``kv_len`` is given, ``j < kv_len[b]``.
    ``q_pos`` is int32 ``[batch, q]`` (absolute position of each query row in
    the cache); ``kv_len`` is int32 ``[batch]`` (exclusive bound of rows ever
    written). The serving engine hands this to
    ``scaled_dot_product_attention`` instead of a dense ``[b, 1, q, max_len]``
    additive mask: the blockwise/Pallas paths consume the lengths directly and
    the einsum fallback expands the mask on the fly in the compute dtype.
    """

    __slots__ = ("q_pos", "kv_len")

    def __init__(self, q_pos, kv_len=None):
        self.q_pos = jnp.asarray(q_pos, jnp.int32)
        self.kv_len = None if kv_len is None else jnp.asarray(kv_len,
                                                              jnp.int32)

    def valid(self, sk):
        """Boolean ``[b, 1, q, sk]`` validity (broadcasts over heads)."""
        col = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, sk), 3)
        ok = col <= self.q_pos[:, None, :, None]
        if self.kv_len is not None:
            ok = ok & (col < self.kv_len[:, None, None, None])
        return ok

    def additive(self, sk, dtype, mask_min=-1e9):
        """Dense additive mask materialized on the fly in ``dtype`` — the
        short-sequence fallback; never an fp32 constant the compiler could
        fold and hold in HBM."""
        return jnp.where(self.valid(sk), jnp.asarray(0.0, dtype),
                         jnp.asarray(mask_min, dtype))


def _pick_block(n, pref):
    """Largest divisor of ``n`` that is <= ``pref`` (no padding: padding a
    KV cache block would copy the cache)."""
    for c in range(min(int(pref), n), 0, -1):
        if n % c == 0:
            return c
    return 1


# ---------------------------------------------------------------------------
# blockwise online-softmax scan (runs on every backend, incl. XLA:CPU)
# ---------------------------------------------------------------------------

def _bw_fwd(q, k, v, q_pos, kv_len, scale, block_k):
    """Forward scan over KV blocks. Carry: running (max, denom, acc) per
    query row; the only O(block)-wide temporary is the ``[b, h, sq,
    block_k]`` score tile of the current block."""
    f32 = jnp.float32
    b, sq, h, d = q.shape
    sk = k.shape[1]
    nb = sk // block_k
    qf = jnp.swapaxes(q, 1, 2).astype(f32) * scale            # [b,h,sq,d]
    ks = jnp.moveaxis(
        jnp.swapaxes(k, 1, 2).astype(f32).reshape(b, h, nb, block_k, d), 2, 0)
    vs = jnp.moveaxis(
        jnp.swapaxes(v, 1, 2).astype(f32).reshape(b, h, nb, block_k, d), 2, 0)
    base = jnp.arange(nb, dtype=jnp.int32) * block_k
    qpos_e = q_pos[:, None, :, None]
    klen_e = None if kv_len is None else kv_len[:, None, None, None]

    def body(carry, xs):
        m, l, acc = carry
        kb, vb, b0 = xs
        s_ = jnp.einsum("bhqd,bhkd->bhqk", qf, kb)
        col = b0 + jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, block_k), 3)
        ok = col <= qpos_e
        if klen_e is not None:
            ok = ok & (col < klen_e)
        s_ = jnp.where(ok, s_, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_, axis=-1))
        # masked entries must contribute 0 even when the whole row is masked
        # so far (m_new == NEG_INF would make exp(s - m_new) = 1)
        p = jnp.where(ok, jnp.exp(s_ - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        return (m_new, l, acc), None

    m0 = jnp.full((b, h, sq), NEG_INF, f32)
    l0 = jnp.zeros((b, h, sq), f32)
    a0 = jnp.zeros((b, h, sq, d), f32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), (ks, vs, base))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.swapaxes(out, 1, 2).astype(q.dtype), lse


def _bw_bwd(q, k, v, q_pos, kv_len, out, lse, g, scale, block_q, block_k):
    """FlashAttention-2 recurrence: dq scans K blocks, dk/dv scan Q blocks;
    every score tile is recomputed from the saved logsumexp so nothing
    O(sq·sk) is ever live."""
    f32 = jnp.float32
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qf = jnp.swapaxes(q, 1, 2).astype(f32)
    kf = jnp.swapaxes(k, 1, 2).astype(f32)
    vf = jnp.swapaxes(v, 1, 2).astype(f32)
    gf = jnp.swapaxes(g, 1, 2).astype(f32)
    of = jnp.swapaxes(out, 1, 2).astype(f32)
    delta = jnp.sum(of * gf, axis=-1)                         # [b,h,sq]
    qpos_e = q_pos[:, None, :, None]
    klen_e = None if kv_len is None else kv_len[:, None, None, None]

    nbk = sk // block_k
    ks = jnp.moveaxis(kf.reshape(b, h, nbk, block_k, d), 2, 0)
    vs = jnp.moveaxis(vf.reshape(b, h, nbk, block_k, d), 2, 0)
    basek = jnp.arange(nbk, dtype=jnp.int32) * block_k

    def dq_body(dq, xs):
        kb, vb, b0 = xs
        s_ = jnp.einsum("bhqd,bhkd->bhqk", qf, kb) * scale
        col = b0 + jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, block_k), 3)
        ok = col <= qpos_e
        if klen_e is not None:
            ok = ok & (col < klen_e)
        p = jnp.where(ok, jnp.exp(s_ - lse[..., None]), 0.0)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vb)
        ds = p * (dp - delta[..., None])
        return dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb) * scale, None

    dq, _ = jax.lax.scan(dq_body, jnp.zeros((b, h, sq, d), f32),
                         (ks, vs, basek))

    nbq = sq // block_q
    qs = jnp.moveaxis(qf.reshape(b, h, nbq, block_q, d), 2, 0)
    gs = jnp.moveaxis(gf.reshape(b, h, nbq, block_q, d), 2, 0)
    ls = jnp.moveaxis(lse.reshape(b, h, nbq, block_q), 2, 0)
    dls = jnp.moveaxis(delta.reshape(b, h, nbq, block_q), 2, 0)
    pqs = jnp.moveaxis(q_pos.reshape(b, nbq, block_q), 1, 0)
    colk = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, sk), 3)

    def dkv_body(carry, xs):
        dk, dv = carry
        qb, gb, lb, db, pq = xs
        s_ = jnp.einsum("bhqd,bhkd->bhqk", qb, kf) * scale
        ok = colk <= pq[:, None, :, None]
        if klen_e is not None:
            ok = ok & (colk < klen_e)
        p = jnp.where(ok, jnp.exp(s_ - lb[..., None]), 0.0)
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", p, gb)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gb, vf)
        ds = p * (dp - db[..., None])
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", ds, qb) * scale
        return (dk, dv), None

    z = jnp.zeros((b, h, sk, d), f32)
    (dk, dv), _ = jax.lax.scan(dkv_body, (z, z), (qs, gs, ls, dls, pqs))

    def back(x, dt):
        return jnp.swapaxes(x, 1, 2).astype(dt)

    return back(dq, q.dtype), back(dk, k.dtype), back(dv, v.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _blockwise(q, k, v, q_pos, kv_len, scale, block_q, block_k):
    out, _ = _bw_fwd(q, k, v, q_pos, kv_len, scale, block_k)
    return out


def _blockwise_vjp_fwd(q, k, v, q_pos, kv_len, scale, block_q, block_k):
    # the custom vjp is mandatory, not an optimization: naive AD of the scan
    # would stack the per-block probability tiles into an O(sq·sk) residual
    out, lse = _bw_fwd(q, k, v, q_pos, kv_len, scale, block_k)
    return out, (q, k, v, q_pos, kv_len, out, lse)


def _blockwise_vjp_bwd(scale, block_q, block_k, res, g):
    q, k, v, q_pos, kv_len, out, lse = res
    dq, dk, dv = _bw_bwd(q, k, v, q_pos, kv_len, out, lse, g, scale,
                         block_q, block_k)
    zp = np.zeros(q_pos.shape, dtype=jax.dtypes.float0)
    zl = (None if kv_len is None
          else np.zeros(kv_len.shape, dtype=jax.dtypes.float0))
    return dq, dk, dv, zp, zl


_blockwise.defvjp(_blockwise_vjp_fwd, _blockwise_vjp_bwd)


@op("blockwise_sdpa")
def _sdpa_blockwise(q, k, v, q_pos, kv_len=None, scale=None, block_q=0,
                    block_k=0):
    """Blockwise online-softmax attention (q,k,v in paddle (b,s,h,d)
    layout). ``q_pos``/``kv_len`` follow :class:`LengthMask` semantics."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _blockwise(q, k, v, q_pos, kv_len, s, block_q, block_k)


def _blockwise_ok(q_shape, k_shape, dropout_p, training):
    """Blockwise path: no attention dropout (the scan has no in-kernel PRNG)
    and at least ``blockwise_attention_min_kv`` key slots — below that the
    fused einsum is faster and its score matrix is small anyway."""
    from ...framework.flags import flag_value

    if flag_value("disable_blockwise_attention"):
        return False
    if dropout_p > 0.0 and training:
        return False
    return k_shape[1] >= flag_value("blockwise_attention_min_kv")


def _blockwise_blocks(sq, sk):
    from ...framework.flags import flag_value

    bq = _pick_block(sq, flag_value("blockwise_attention_block_q") or 512)
    bk = _pick_block(sk, flag_value("blockwise_attention_block_k") or 512)
    return bq, bk


def _route_length_masked(query, key, value, lm, dropout_p, training, scale):
    """Cached-attention routing: Pallas length-masked kernel when the shapes
    tile onto the MXU, blockwise scan otherwise, dense on-the-fly mask below
    the min-kv threshold (or under attention dropout)."""
    b, sq, h, d = query.shape
    sk = key.shape[1]
    active_p = dropout_p if training else 0.0
    if _blockwise_ok(query.shape, key.shape, dropout_p, training):
        from ...ops import pallas

        s = scale if scale is not None else 1.0 / math.sqrt(d)
        if pallas.is_available():
            from ...ops.pallas.flash_attention import supports_cached

            if supports_cached(sq, sk, d):
                return _sdpa_flash_cached(query, key, value, lm.q_pos,
                                          lm.kv_len, scale=s)
        bq, bk = _blockwise_blocks(sq, sk)
        return _sdpa_blockwise(query, key, value, lm.q_pos, lm.kv_len,
                               scale=s, block_q=bq, block_k=bk)
    mask = lm.additive(sk, query.dtype)
    dropout_mask = None
    if active_p > 0.0:
        dropout_mask = jax.random.bernoulli(
            rnd.next_key(), 1.0 - active_p, (b, h, sq, sk))
    return _sdpa_raw(query, key, value, mask, dropout_mask, causal=False,
                     scale=scale, dropout_p=active_p)


def _flash_ok(q_shape, k_shape, mask, dropout_p, training, mask_trainable=False):
    """Pallas flash path: TPU (or interpret-mode) backend, MXU-tileable
    sequence lengths, and — when a mask is given — a mask the kernel streams
    exactly: trailing dims ``(sq, sk)`` with broadcastable batch/head dims.
    Trainable biases are supported: the fused backward computes the real
    dS-sum bias gradient (XLA-DCE'd when unused). Attention dropout runs
    in-kernel via the TPU hardware PRNG — compiled-TPU only (no interpret
    lowering) and incompatible with a trainable bias (the XLA dbias
    recompute cannot regenerate the in-kernel mask)."""
    from ...framework.flags import flag_value
    from ...ops import pallas

    if flag_value("disable_flash_attention"):
        return False
    if dropout_p > 0.0 and training:
        if pallas.interpret_requested() or mask_trainable:
            return False
    sq, sk = q_shape[1], k_shape[1]
    # Routing by measured crossover (v5e): below sq*sk = 1024^2 XLA's fused
    # einsum attention wins; at 1024^2+ the Pallas kernel with 1024-wide
    # blocks is faster (GPT-2 s=1024 end-to-end: 102.6k vs 88.0k tok/s) and
    # keeps memory flat at long context.
    if sq * sk < flag_value("flash_attention_min_seq_prod") and not pallas.interpret_requested():
        return False
    if mask is not None:
        ms = tuple(mask.shape)
        if len(ms) == 4:
            if ms[2:] != (sq, sk):
                return False
            if ms[0] not in (1, q_shape[0]) or ms[1] not in (1, q_shape[2]):
                return False
        elif ms != (sq, sk):
            return False
    if not pallas.is_available():
        return False
    from ...ops.pallas.flash_attention import supports

    return supports(sq, sk, q_shape[3])


@op("flash_sdpa")
def _sdpa_flash(q, k, v, mask=None, dropout_seed=None, causal=False,
                scale=None, mask_trainable=False, dropout_p=0.0):
    """q,k,v: (batch, seq, heads, head_dim) — paddle layout.

    Prefers the seq-major packed kernel (zero layout transposes — the
    (b,s,h,d)->(b,s,h*d) reshape is free) whenever the head dim packs into
    128-lane groups and the mask is shared-2-D/absent; per-batch/per-head
    or trainable biases take the layout-swapping kernel."""
    from ...ops.pallas import flash_attention_packed as packed
    from ...ops.pallas.flash_attention import flash_attention as fa

    b, sq, h, d = q.shape
    sk = k.shape[1]
    mask_2d = mask is not None and mask.ndim == 2
    if ((mask is None or (mask_2d and not mask_trainable))
            and packed.supports(sq, sk, h, h * d)):
        out = packed.flash_attention_packed(
            q.reshape(b, sq, h * d), k.reshape(b, sk, h * d),
            v.reshape(b, sk, h * d), h, bias=mask, causal=causal,
            scale=scale, dropout_p=dropout_p, dropout_seed=dropout_seed)
        return out.reshape(b, sq, h, d)
    return fa(q, k, v, bias=mask, causal=causal, scale=scale,
              bias_grad=mask_trainable,
              dropout_p=dropout_p, dropout_seed=dropout_seed)


@op("flash_sdpa_cached")
def _sdpa_flash_cached(q, k, v, q_pos, kv_len=None, scale=None):
    """Pallas length-masked (cached-attention) kernel — inference path; the
    per-tile validity comes from the streamed positions, never a dense
    bias."""
    from ...ops.pallas.flash_attention import flash_attention_cached

    return flash_attention_cached(q, k, v, q_pos, kv_len, scale=scale)


@op("sdpa")
def _sdpa_raw(q, k, v, mask=None, dropout_mask=None, causal=False, scale=None,
              dropout_p=0.0):
    """XLA einsum path (small/odd shapes, or attention dropout active).

    ``dropout_mask`` is a keep-mask drawn by the caller (so the op stays a
    pure function of its inputs and remains jit-traceable).
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if causal:
        # iota compare, not jnp.tril of a ones constant: the latter const-
        # folds into an fp32 [s, s] executable constant charged against HBM
        # (O(seq²) bytes at 32k — the hbm-const-folded finding)
        ql, kl = logits.shape[-2], logits.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (ql, kl), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (ql, kl), 1)
        logits = jnp.where(col - row <= kl - ql, logits,
                           jnp.asarray(NEG_INF, logits.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_mask is not None:
        probs = probs * dropout_mask.astype(probs.dtype) / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _sdpa(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
          training=True, scale=None):
    if isinstance(attn_mask, LengthMask):
        return _route_length_masked(query, key, value, attn_mask, dropout_p,
                                    training, scale)
    trainable = (attn_mask is not None
                 and getattr(attn_mask, "stop_gradient", True) is False)
    if _flash_ok(query.shape, key.shape, attn_mask, dropout_p, training,
                 trainable):
        active_p = dropout_p if training else 0.0
        seed = None
        if active_p > 0.0:
            # two 32-bit words of a fresh key seed the in-kernel PRNG
            seed = jax.lax.bitcast_convert_type(
                jax.random.bits(rnd.next_key(), (2,), jnp.uint32), jnp.int32
            )
        return _sdpa_flash(query, key, value, attn_mask, seed,
                           causal=is_causal, scale=scale,
                           mask_trainable=trainable, dropout_p=active_p)
    if (attn_mask is None and is_causal
            and _blockwise_ok(query.shape, key.shape, dropout_p, training)):
        # long causal training without Pallas (e.g. XLA:CPU): blockwise scan
        # instead of the O(seq²) einsum score matrix
        b, sq, _, d = query.shape
        sk = key.shape[1]
        s = scale if scale is not None else 1.0 / math.sqrt(d)
        q_pos = jnp.broadcast_to(
            jnp.arange(sk - sq, sk, dtype=jnp.int32)[None, :], (b, sq))
        bq, bk = _blockwise_blocks(sq, sk)
        return _sdpa_blockwise(query, key, value, q_pos, None, scale=s,
                               block_q=bq, block_k=bk)
    dropout_mask = None
    if dropout_p > 0.0 and training:
        b, sq, h, _ = query.shape
        sk = key.shape[1]
        dropout_mask = jax.random.bernoulli(
            rnd.next_key(), 1.0 - dropout_p, (b, h, sq, sk)
        )
    return _sdpa_raw(query, key, value, attn_mask, dropout_mask,
                     causal=is_causal, scale=scale, dropout_p=dropout_p)


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
    training=True, name=None
):
    return _sdpa(query, key, value, attn_mask, dropout_p, is_causal, training)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    out = _sdpa(query, key, value, None, dropout, causal, training)
    return out, None
