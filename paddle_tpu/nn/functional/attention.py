"""Attention functionals.

Reference fused kernels: ``paddle/fluid/operators/fused/fused_attention_op.cu``
and ``fmha_ref.h``. TPU-native path: the Pallas flash-attention kernel
(``paddle_tpu.ops.pallas.flash_attention``) whenever shapes tile onto the MXU
and no attention dropout is requested; an XLA einsum path otherwise.

Routing is an EXPLICIT capability check (``_flash_ok``), never a silent
``except`` fallback: if the Pallas kernel is selected and fails, the error
propagates.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework import random as rnd
from ...ops.dispatch import op


def _flash_ok(q_shape, k_shape, mask, dropout_p, training, mask_trainable=False):
    """Pallas flash path: TPU (or interpret-mode) backend, MXU-tileable
    sequence lengths, and — when a mask is given — a mask the kernel streams
    exactly: trailing dims ``(sq, sk)`` with broadcastable batch/head dims.
    Trainable biases are supported: the fused backward computes the real
    dS-sum bias gradient (XLA-DCE'd when unused). Attention dropout runs
    in-kernel via the TPU hardware PRNG — compiled-TPU only (no interpret
    lowering) and incompatible with a trainable bias (the XLA dbias
    recompute cannot regenerate the in-kernel mask)."""
    from ...framework.flags import flag_value
    from ...ops import pallas

    if flag_value("disable_flash_attention"):
        return False
    if dropout_p > 0.0 and training:
        if pallas.interpret_requested() or mask_trainable:
            return False
    sq, sk = q_shape[1], k_shape[1]
    # Routing by measured crossover (v5e): below sq*sk = 1024^2 XLA's fused
    # einsum attention wins; at 1024^2+ the Pallas kernel with 1024-wide
    # blocks is faster (GPT-2 s=1024 end-to-end: 102.6k vs 88.0k tok/s) and
    # keeps memory flat at long context.
    if sq * sk < flag_value("flash_attention_min_seq_prod") and not pallas.interpret_requested():
        return False
    if mask is not None:
        ms = tuple(mask.shape)
        if len(ms) == 4:
            if ms[2:] != (sq, sk):
                return False
            if ms[0] not in (1, q_shape[0]) or ms[1] not in (1, q_shape[2]):
                return False
        elif ms != (sq, sk):
            return False
    if not pallas.is_available():
        return False
    from ...ops.pallas.flash_attention import supports

    return supports(sq, sk, q_shape[3])


@op("flash_sdpa")
def _sdpa_flash(q, k, v, mask=None, dropout_seed=None, causal=False,
                scale=None, mask_trainable=False, dropout_p=0.0):
    """q,k,v: (batch, seq, heads, head_dim) — paddle layout.

    Prefers the seq-major packed kernel (zero layout transposes — the
    (b,s,h,d)->(b,s,h*d) reshape is free) whenever the head dim packs into
    128-lane groups and the mask is shared-2-D/absent; per-batch/per-head
    or trainable biases take the layout-swapping kernel."""
    from ...ops.pallas import flash_attention_packed as packed
    from ...ops.pallas.flash_attention import flash_attention as fa

    b, sq, h, d = q.shape
    sk = k.shape[1]
    mask_2d = mask is not None and mask.ndim == 2
    if ((mask is None or (mask_2d and not mask_trainable))
            and packed.supports(sq, sk, h, h * d)):
        out = packed.flash_attention_packed(
            q.reshape(b, sq, h * d), k.reshape(b, sk, h * d),
            v.reshape(b, sk, h * d), h, bias=mask, causal=causal,
            scale=scale, dropout_p=dropout_p, dropout_seed=dropout_seed)
        return out.reshape(b, sq, h, d)
    return fa(q, k, v, bias=mask, causal=causal, scale=scale,
              bias_grad=mask_trainable,
              dropout_p=dropout_p, dropout_seed=dropout_seed)


@op("sdpa")
def _sdpa_raw(q, k, v, mask=None, dropout_mask=None, causal=False, scale=None,
              dropout_p=0.0):
    """XLA einsum path (small/odd shapes, or attention dropout active).

    ``dropout_mask`` is a keep-mask drawn by the caller (so the op stays a
    pure function of its inputs and remains jit-traceable).
    """
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_mask is not None:
        probs = probs * dropout_mask.astype(probs.dtype) / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _sdpa(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
          training=True, scale=None):
    trainable = (attn_mask is not None
                 and getattr(attn_mask, "stop_gradient", True) is False)
    if _flash_ok(query.shape, key.shape, attn_mask, dropout_p, training,
                 trainable):
        active_p = dropout_p if training else 0.0
        seed = None
        if active_p > 0.0:
            # two 32-bit words of a fresh key seed the in-kernel PRNG
            seed = jax.lax.bitcast_convert_type(
                jax.random.bits(rnd.next_key(), (2,), jnp.uint32), jnp.int32
            )
        return _sdpa_flash(query, key, value, attn_mask, seed,
                           causal=is_causal, scale=scale,
                           mask_trainable=trainable, dropout_p=active_p)
    dropout_mask = None
    if dropout_p > 0.0 and training:
        b, sq, h, _ = query.shape
        sk = key.shape[1]
        dropout_mask = jax.random.bernoulli(
            rnd.next_key(), 1.0 - dropout_p, (b, h, sq, sk)
        )
    return _sdpa_raw(query, key, value, attn_mask, dropout_mask,
                     causal=is_causal, scale=scale, dropout_p=dropout_p)


def scaled_dot_product_attention(
    query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
    training=True, name=None
):
    return _sdpa(query, key, value, attn_mask, dropout_p, is_causal, training)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    out = _sdpa(query, key, value, None, dropout, causal, training)
    return out, None
