"""Loss functionals (reference ``python/paddle/nn/functional/loss.py``;
softmax+CE fused kernel ``paddle/phi/kernels/gpu/cross_entropy_kernel.cu`` —
here the log-softmax+gather form which XLA fuses)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ... import ops
from ...framework.tensor import Tensor
from ...ops.dispatch import op, ensure_tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@op("softmax_ce")
def _softmax_ce_raw(logits, label, soft_label=False, axis=-1, ignore_index=-100,
                    use_ignore=False, reduction="none", ls_epsilon=0.0):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        tgt = label
        if ls_epsilon > 0.0:
            n = logits.shape[axis]
            tgt = (1 - ls_epsilon) * tgt + ls_epsilon / n
        loss = -jnp.sum(tgt * logp, axis=axis)
    else:
        lab = label
        if lab.ndim == logp.ndim:
            lab = jnp.squeeze(lab, axis)
        lab_i = lab.astype(jnp.int32)
        safe = jnp.where(lab_i < 0, 0, lab_i)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
        loss = -jnp.squeeze(picked, axis)
        if use_ignore:
            mask = lab_i != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    if not use_softmax:
        return nll_loss_from_probs(input, label, weight, ignore_index, reduction, soft_label, axis)
    if weight is not None:
        # weighted path: per-class weights gathered by label
        logp = log_softmax_t(input, axis)
        lab = label
        if lab.ndim == input.ndim:
            from ...ops import manipulation as man

            lab = man.squeeze(lab, axis)
        return _weighted_nll(logp, lab, weight, ignore_index=ignore_index, reduction=reduction, axis=axis)
    return _softmax_ce_raw(
        input,
        label,
        soft_label=soft_label,
        axis=int(axis),
        ignore_index=ignore_index,
        use_ignore=not soft_label,
        reduction=reduction,
        ls_epsilon=label_smoothing,
    )


def log_softmax_t(x, axis):
    from .activation import log_softmax

    return log_softmax(x, axis)


@op("weighted_nll")
def _weighted_nll(logp, label, weight, ignore_index=-100, reduction="mean", axis=-1):
    lab_i = label.astype(jnp.int32)
    safe = jnp.where(lab_i < 0, 0, lab_i)
    picked = -jnp.squeeze(jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis), axis)
    w = jnp.take(weight, safe)
    mask = (lab_i != ignore_index).astype(logp.dtype)
    loss = picked * w * mask
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w * mask), 1e-12)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = _softmax_ce_raw(logits, label, soft_label=soft_label, axis=int(axis), ignore_index=ignore_index, use_ignore=not soft_label, reduction="none")
    from ...ops import manipulation as man

    loss = man.unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax

        return loss, softmax(logits, axis)
    return loss


def nll_loss_from_probs(input, label, weight, ignore_index, reduction, soft_label, axis):
    from ...ops import math as m

    logp = m.log(input)
    if soft_label:
        return _soft_nll(logp, label, reduction=reduction, axis=axis)
    if weight is not None:
        return _weighted_nll(logp, label, weight, ignore_index=ignore_index, reduction=reduction, axis=axis)
    return nll_loss(logp, label, reduction=reduction, ignore_index=ignore_index)


@op("soft_nll")
def _soft_nll(logp, label, reduction="mean", axis=-1):
    loss = -jnp.sum(label * logp, axis=axis)
    return _reduce(loss, reduction)


@op("nll_loss_op")
def _nll_raw(logp, label, ignore_index=-100, reduction="mean", has_weight=False, weight=None):
    lab = label.astype(jnp.int32)
    safe = jnp.where(lab < 0, 0, lab)
    # class axis is 1 for nll_loss (N, C, ...)
    picked = -jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
    picked = jnp.squeeze(picked, 1)
    mask = (lab != ignore_index).astype(logp.dtype)
    if has_weight:
        w = jnp.take(weight, safe) * mask
        loss = picked * w
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
        return _reduce(loss, reduction)
    loss = picked * mask
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1.0)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    if weight is not None:
        return _nll_weighted_raw(input, label, weight, ignore_index=ignore_index, reduction=reduction)
    return _nll_raw(input, label, ignore_index=ignore_index, reduction=reduction)


@op("nll_loss_weighted")
def _nll_weighted_raw(logp, label, weight, ignore_index=-100, reduction="mean"):
    lab = label.astype(jnp.int32)
    safe = jnp.where(lab < 0, 0, lab)
    picked = -jnp.squeeze(jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1), 1)
    mask = (lab != ignore_index).astype(logp.dtype)
    w = jnp.take(weight, safe) * mask
    loss = picked * w
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    return _reduce(loss, reduction)


@op("mse_loss_op")
def _mse_raw(input, label, reduction="mean"):
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse_raw(input, label, reduction=reduction)


@op("l1_loss_op")
def _l1_raw(input, label, reduction="mean"):
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1_raw(input, label, reduction=reduction)


@op("smooth_l1_op")
def _smooth_l1_raw(input, label, reduction="mean", delta=1.0):
    d = input - label
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1_raw(input, label, reduction=reduction, delta=delta)


def huber_loss(input, label, delta=1.0, reduction="mean"):
    return _smooth_l1_raw(input, label, reduction=reduction, delta=delta)


@op("bce_op")
def _bce_raw(input, label, reduction="mean", has_weight=False, weight=None, eps=1e-12):
    loss = -(label * jnp.log(jnp.maximum(input, eps)) + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if has_weight:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    if weight is not None:
        return _bce_raw(input, label, weight, reduction=reduction, has_weight=True)
    return _bce_raw(input, label, reduction=reduction)


@op("bce_logits_op")
def _bce_logits_raw(logit, label, reduction="mean", has_weight=False, weight=None, has_pos=False, pos_weight=None):
    max_val = jnp.maximum(-logit, 0)
    if has_pos:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if has_weight:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    args = [logit, label]
    kwargs = dict(reduction=reduction)
    if weight is not None:
        args.append(weight)
        kwargs["has_weight"] = True
    if pos_weight is not None:
        args.append(pos_weight)
        kwargs["has_pos"] = True
    # positional protocol: rebuild raw call with keywords mapping
    if weight is not None and pos_weight is not None:
        return _bce_logits_full(logit, label, weight, pos_weight, reduction=reduction)
    if weight is not None:
        return _bce_logits_w(logit, label, weight, reduction=reduction)
    if pos_weight is not None:
        return _bce_logits_p(logit, label, pos_weight, reduction=reduction)
    return _bce_logits_raw(logit, label, reduction=reduction)


@op("bce_logits_w")
def _bce_logits_w(logit, label, weight, reduction="mean"):
    max_val = jnp.maximum(-logit, 0)
    loss = ((1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val) * weight
    return _reduce(loss, reduction)


@op("bce_logits_p")
def _bce_logits_p(logit, label, pos_weight, reduction="mean"):
    max_val = jnp.maximum(-logit, 0)
    log_w = (pos_weight - 1.0) * label + 1.0
    loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    return _reduce(loss, reduction)


@op("bce_logits_full")
def _bce_logits_full(logit, label, weight, pos_weight, reduction="mean"):
    max_val = jnp.maximum(-logit, 0)
    log_w = (pos_weight - 1.0) * label + 1.0
    loss = ((1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)) * weight
    return _reduce(loss, reduction)


@op("kl_div_op")
def _kl_raw(input, label, reduction="mean"):
    loss = label * (jnp.log(jnp.maximum(label, 1e-12)) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    return _kl_raw(input, label, reduction=reduction)


@op("margin_ranking_op")
def _margin_ranking_raw(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return _margin_ranking_raw(input, other, label, margin=margin, reduction=reduction)


@op("hinge_embedding_op")
def _hinge_embedding_raw(input, label, margin=1.0, reduction="mean"):
    loss = jnp.where(label == 1.0, input, jnp.maximum(margin - input, 0))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _hinge_embedding_raw(input, label, margin=margin, reduction=reduction)


@op("cosine_embedding_op")
def _cosine_embedding_raw(input1, input2, label, margin=0.0, reduction="mean"):
    cos = jnp.sum(input1 * input2, -1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1) + 1e-12
    )
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    return _cosine_embedding_raw(input1, input2, label, margin=margin, reduction=reduction)


@op("triplet_margin_op")
def _triplet_raw(anchor, positive, negative, margin=1.0, p=2.0, eps=1e-6, swap=False, reduction="mean"):
    def dist(a, b):
        return jnp.sum(jnp.abs(a - b + eps) ** p, axis=-1) ** (1.0 / p)

    d_pos = dist(anchor, positive)
    d_neg = dist(anchor, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jnp.maximum(d_pos - d_neg + margin, 0), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    return _triplet_raw(input, positive, negative, margin=margin, p=p, eps=epsilon, swap=swap, reduction=reduction)


@op("square_error_cost_op")
def square_error_cost(input, label):
    return jnp.square(input - label)


@op("sigmoid_focal_op")
def _sigmoid_focal_raw(logit, label, gamma=2.0, alpha=0.25, normalizer=None, reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    if normalizer is not None:
        return _sigmoid_focal_n(logit, label, normalizer, gamma=gamma, alpha=alpha, reduction=reduction)
    return _sigmoid_focal_raw(logit, label, gamma=gamma, alpha=alpha, reduction=reduction)


@op("sigmoid_focal_n")
def _sigmoid_focal_n(logit, label, normalizer, gamma=2.0, alpha=0.25, reduction="sum"):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce / normalizer
    return _reduce(loss, reduction)


@op("log_loss_op")
def _log_loss_raw(input, label, epsilon=1e-4):
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)


def log_loss(input, label, epsilon=1e-4, name=None):
    return _log_loss_raw(input, label, epsilon=epsilon)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC loss via dynamic-programming in log space (reference uses warpctc —
    ``paddle/fluid/operators/warpctc_op.cc``). Implemented as a jax scan so it
    compiles on TPU."""
    return _ctc_raw(
        log_probs, labels, input_lengths, label_lengths, blank=blank, reduction=reduction
    )


@op("ctc_op")
def _ctc_raw(logits, labels, input_lengths, label_lengths, blank=0, reduction="mean"):
    # logits: (T, B, C) paddle layout, raw (unnormalized); labels (B, S)
    logp = jax.nn.log_softmax(logits, axis=-1)
    T, B, C = logp.shape
    S = labels.shape[1]
    # extended label seq: blank, l1, blank, l2, ... blank  (length 2S+1)
    ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    ext_len = 2 * label_lengths.astype(jnp.int32) + 1
    NEG = -1e30

    # alpha recursion
    alpha0 = jnp.full((B, 2 * S + 1), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(S > 0, logp[0, jnp.arange(B), ext[:, 1]], NEG)
    )

    same_as_prev2 = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
    )

    def step(alpha, logp_t):
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(same_as_prev2, NEG, a_shift2)
        combined = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)
        return combined + emit, None

    def scan_step(carry, t):
        alpha = carry
        new_alpha, _ = step(alpha, logp[t])
        # freeze past input_lengths
        active = (t < input_lengths)[:, None]
        alpha = jnp.where(active, new_alpha, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
    idx_last = ext_len - 1
    ll_blank = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(alpha, jnp.maximum(idx_last - 1, 0)[:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(ll_blank, ll_label)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(label_lengths, 1))
    return _reduce(loss, reduction)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    from ...ops import math as m

    B = anchor.shape[0]
    sim = m.matmul(anchor, positive, transpose_y=True)
    lab = labels.reshape([-1, 1])
    tgt = (lab == lab.T).astype(sim.dtype)
    tgt = tgt / tgt.sum(axis=1, keepdim=True)
    ce = cross_entropy(sim, tgt, soft_label=True)
    l2 = m.mean(m.sum(m.square(anchor), axis=1)) + m.mean(m.sum(m.square(positive), axis=1))
    return ce + m.multiply(l2, l2_reg * 0.25)


def dice_loss(input, label, epsilon=1e-5, name=None):
    from ...ops import math as m
    from .common import one_hot

    lab = one_hot(label.squeeze(-1), input.shape[-1])
    inter = m.sum(m.multiply(input, lab), axis=tuple(range(1, input.ndim)))
    union = m.sum(input, axis=tuple(range(1, input.ndim))) + m.sum(lab, axis=tuple(range(1, lab.ndim)))
    dice = m.divide(m.multiply(inter, 2.0), m.add(union, epsilon))
    return m.mean(m.subtract(ensure_tensor(1.0, like=dice), dice))


# -- round-4 API-audit additions --------------------------------------------

def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """Per-class soft-margin BCE averaged over classes (reference
    ``nn/functional/loss.py multi_label_soft_margin_loss``)."""
    from ...ops.dispatch import apply_op

    args = (input, label) if weight is None else (input, label, weight)

    def fwd(x, y, w=None):
        ls = jax.nn.log_sigmoid
        per = -(y * ls(x) + (1.0 - y) * ls(-x))
        if w is not None:
            per = per * w
        per = jnp.mean(per, axis=-1)
        if reduction == "none":
            return per
        return jnp.sum(per) if reduction == "sum" else jnp.mean(per)

    return apply_op("multi_label_soft_margin_loss", fwd, args, {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference ``nn/functional/loss.py triplet_margin_with_distance_loss``."""
    from ...ops.dispatch import apply_op

    if distance_function is not None:
        # user metric operates on Tensors — compute eagerly through it
        dp = distance_function(input, positive)
        dn = distance_function(input, negative)
        if swap:
            dsn = distance_function(positive, negative)
            dn = ops.minimum(dn, dsn)
        loss = ops.clip(dp - dn + margin, min=0.0)
        if reduction == "none":
            return loss
        return loss.sum() if reduction == "sum" else loss.mean()

    def fwd(a, p, n):
        def dist(u, v):
            return jnp.sqrt(jnp.sum((u - v) ** 2, axis=-1) + 1e-12)

        dp, dn = dist(a, p), dist(a, n)
        if swap:
            dn = jnp.minimum(dn, dist(p, n))
        loss = jnp.maximum(dp - dn + margin, 0.0)
        if reduction == "none":
            return loss
        return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)

    return apply_op("triplet_margin_with_distance_loss", fwd,
                    (input, positive, negative), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference ``nn/functional/loss.py
    hsigmoid_loss`` / ``phi/kernels hsigmoid``). Default tree: the complete
    binary tree over ``num_classes`` leaves whose internal nodes are
    heap-indexed (leaf ``c`` sits at heap position ``c + num_classes - 1``;
    internal nodes 0..num_classes-2 own one weight row each); custom
    ``path_table``/``path_code`` override it."""
    from ...ops.dispatch import apply_op

    import math as _math

    depth = max(1, _math.ceil(_math.log2(max(2, num_classes))))
    if path_table is None:
        # precompute the (num_classes, depth) table on host: node ids along
        # the root->leaf path (-1 pads short paths) and left/right codes
        tab = np.full((num_classes, depth), -1, np.int32)
        code = np.zeros((num_classes, depth), np.float32)
        for c in range(num_classes):
            node = c + num_classes - 1
            path = []
            while node > 0:
                parent = (node - 1) // 2
                path.append((parent, float(node == 2 * parent + 2)))
                node = parent
            for i, (nid, bit) in enumerate(reversed(path)):
                tab[c, i] = nid
                code[c, i] = bit
        path_table_v, path_code_v = jnp.asarray(tab), jnp.asarray(code)
    else:
        path_table_v = jnp.asarray(
            path_table._value if isinstance(path_table, Tensor) else path_table)
        path_code_v = jnp.asarray(
            path_code._value if isinstance(path_code, Tensor) else path_code)

    args = (input, label, weight) if bias is None else (input, label, weight,
                                                        bias)

    def fwd(x, y, w, b=None):
        nodes = path_table_v[y]                      # [N, D]
        codes = path_code_v[y].astype(x.dtype)       # [N, D]
        valid = (nodes >= 0).astype(x.dtype)
        safe_nodes = jnp.maximum(nodes, 0)
        wn = w[safe_nodes]                           # [N, D, F]
        logits = jnp.einsum("nf,ndf->nd", x, wn)
        if b is not None:
            logits = logits + b.reshape(-1)[safe_nodes]
        # per-node BCE with target = code; reference returns [N, 1]
        per = jax.nn.softplus(logits) - codes * logits
        return jnp.sum(per * valid, axis=-1, keepdims=True)

    return apply_op("hsigmoid_loss", fwd, args, {})


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """Combined-margin (ArcFace-family) softmax CE over cosine logits
    (reference ``nn/functional/loss.py:1701``): target-class logit becomes
    ``cos(m1*theta + m2) - m3``, all logits scaled by ``scale``. Works on
    the mp group's sharded classes in spmd contexts via the regular
    parallel CE; single-controller path here operates on full logits."""
    from ...ops.dispatch import apply_op

    def fwd(lg, y):
        y = y.reshape(-1)          # reference accepts [N] or [N, 1]
        n, c = lg.shape
        theta = jnp.arccos(jnp.clip(lg, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y, c, dtype=lg.dtype)
        adj = (lg * (1.0 - onehot) + target * onehot) * scale
        lse = jax.nn.logsumexp(adj, axis=-1)
        picked = jnp.sum(adj * onehot, axis=-1)
        loss = lse - picked
        if reduction == "none":
            loss_out = loss[:, None]
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = jnp.mean(loss)
        if return_softmax:
            return loss_out, jax.nn.softmax(adj, axis=-1)
        return loss_out

    return apply_op("margin_cross_entropy", fwd, (logits, label), {})
