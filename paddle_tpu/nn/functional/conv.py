"""Convolutions (reference ``python/paddle/nn/functional/conv.py``; CUDA path
``paddle/phi/kernels/gpudnn/conv_kernel.cu``). Here a single
``lax.conv_general_dilated`` lowering — XLA tiles it onto the MXU and picks
the layout; we keep paddle's NCHW-default API."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ...ops.dispatch import op


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = list(v)
    if len(v) == 1:
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(x) for x in v)


def _norm_padding(padding, n):
    """Returns (lax padding spec, is_same)."""
    if isinstance(padding, str):
        return padding.upper(), True
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n, False
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding], False
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)], False
    # nested [[l, r], ...] possibly including batch/channel dims
    flat = [list(p) if isinstance(p, (list, tuple)) else [p, p] for p in padding]
    if len(flat) == n + 2:
        flat = flat[2:] if flat[0] == [0, 0] else flat[-n:]
    return [(int(l), int(r)) for l, r in flat[:n]], False


def _dim_numbers(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


@op("conv_nd")
def _conv_raw(
    x,
    weight,
    bias=None,
    stride=(1,),
    padding="VALID",
    dilation=(1,),
    groups=1,
    channel_last=False,
    nd=2,
):
    # paddle weight layout is always [out_c, in_c/groups, *k] (OIHW);
    # transpose for channel-last spec
    lhs_spec, rhs_spec, out_spec = _dim_numbers(nd, channel_last)
    if channel_last:
        # OIHW -> HWIO
        perm = list(range(2, 2 + nd)) + [1, 0]
        weight = jnp.transpose(weight, perm)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (lhs_spec, rhs_spec, out_spec))
    out = lax.conv_general_dilated(
        x,
        weight,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[out.ndim - 1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nd):
    channel_last = data_format.endswith("C")
    pad_spec, _ = _norm_padding(padding, nd)
    if not channel_last:
        from ...framework.layout_autotune import layout_autotune_enabled

        if layout_autotune_enabled():
            # NCHW request under layout autotune: run the conv in NHWC (the
            # TPU-preferred layout; reference imperative/layout_autotune.cc)
            # and transpose back at the boundary
            to_last = [0] + list(range(2, nd + 2)) + [1]
            to_first = [0, nd + 1] + list(range(1, nd + 1))
            out = _conv_raw(
                x.transpose(to_last),
                weight,
                *([bias] if bias is not None else []),
                stride=_norm_tuple(stride, nd),
                padding=pad_spec,
                dilation=_norm_tuple(dilation, nd),
                groups=groups,
                channel_last=True,
                nd=nd,
            )
            return out.transpose(to_first)
    return _conv_raw(
        x,
        weight,
        *([bias] if bias is not None else []),
        stride=_norm_tuple(stride, nd),
        padding=pad_spec,
        dilation=_norm_tuple(dilation, nd),
        groups=groups,
        channel_last=channel_last,
        nd=nd,
    )


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, df, 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


@op("conv_transpose_nd")
def _conv_transpose_raw(
    x,
    weight,
    bias=None,
    stride=(1,),
    padding=((0, 0),),
    output_padding=(0,),
    dilation=(1,),
    groups=1,
    channel_last=False,
    nd=2,
):
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    lhs_spec, rhs_spec, out_spec = _dim_numbers(nd, channel_last)
    # Build transposed conv as lhs-dilated conv (the standard XLA lowering):
    # flip spatial dims of the kernel and swap I/O.
    spatial_axes = tuple(range(2, 2 + nd))
    w = jnp.flip(weight, spatial_axes)  # [in_c, out_c/groups, *k]
    if groups > 1:
        # [g*icg, ocg, *k] -> [g*ocg, icg, *k]
        icg = w.shape[0] // groups
        ocg = w.shape[1]
        w = w.reshape(groups, icg, ocg, *w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape(groups * ocg, icg, *w.shape[3:])
    else:
        w = jnp.swapaxes(w, 0, 1)  # [out_c, in_c, *k]
    kernel_spatial = w.shape[2:]  # OIHW layout here
    if channel_last:
        perm = list(range(2, 2 + nd)) + [1, 0]
        w = jnp.transpose(w, perm)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
    if isinstance(padding, str):
        raise ValueError("SAME padding unsupported for conv_transpose; pass ints")
    # effective padding for the dilated-input conv
    eff_pad = []
    for i in range(nd):
        ke = dilation[i] * (kernel_spatial[i] - 1) + 1
        pl, pr = padding[i]
        eff_pad.append((ke - 1 - pl, ke - 1 - pr + output_padding[i]))
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(1,) * nd,
        padding=eff_pad,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        bshape = [1] * out.ndim
        bshape[out.ndim - 1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(bshape)
    return out


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, nd, output_size=None):
    channel_last = data_format.endswith("C")
    pad_spec, is_same = _norm_padding(padding, nd)
    if is_same:
        raise NotImplementedError("string padding for conv_transpose")
    st = _norm_tuple(stride, nd)
    dl = _norm_tuple(dilation, nd)
    opd = _norm_tuple(output_padding, nd)
    if output_size is not None:
        # derive output_padding from requested size
        spatial_in = x.shape[1:-1] if channel_last else x.shape[2:]
        k = weight.shape[2:]
        os_ = output_size if isinstance(output_size, (list, tuple)) else [output_size] * nd
        opd = tuple(
            int(os_[i]) - ((spatial_in[i] - 1) * st[i] - pad_spec[i][0] - pad_spec[i][1] + dl[i] * (k[i] - 1) + 1)
            for i in range(nd)
        )
    return _conv_transpose_raw(
        x,
        weight,
        *([bias] if bias is not None else []),
        stride=st,
        padding=tuple(pad_spec),
        output_padding=opd,
        dilation=dl,
        groups=groups,
        channel_last=channel_last,
        nd=nd,
    )


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, df, 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, data_format, 3, output_size)
