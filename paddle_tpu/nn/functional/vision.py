"""Vision functionals: affine_grid / grid_sample
(reference ``python/paddle/nn/functional/vision.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ...ops.dispatch import op


@op("affine_grid_op")
def _affine_grid_raw(theta, out_shape=(), align_corners=True):
    n, c, h, w = out_shape
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2.0 / h - 1.0
        xs = (jnp.arange(w) + 0.5) * 2.0 / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(1, h * w, 3)
    grid = jnp.einsum("bij,bnj->bni", theta, jnp.broadcast_to(base, (theta.shape[0], h * w, 3)))
    return grid.reshape(theta.shape[0], h, w, 2)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    return _affine_grid_raw(theta, out_shape=tuple(int(s) for s in out_shape), align_corners=align_corners)


@op("grid_sample_op")
def _grid_sample_raw(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        ix = (gx + 1) * (w - 1) / 2
        iy = (gy + 1) * (h - 1) / 2
    else:
        ix = ((gx + 1) * w - 1) / 2
        iy = ((gy + 1) * h - 1) / 2

    def sample(ix_, iy_):
        ix_c = jnp.clip(ix_, 0, w - 1)
        iy_c = jnp.clip(iy_, 0, h - 1)
        valid = ((ix_ >= 0) & (ix_ <= w - 1) & (iy_ >= 0) & (iy_ <= h - 1)).astype(x.dtype)
        bidx = jnp.arange(n).reshape(n, 1, 1)
        vals = x[bidx, :, iy_c.astype(jnp.int32), ix_c.astype(jnp.int32)]
        if padding_mode == "zeros":
            vals = vals * valid[..., None]
        return vals  # (n, gh, gw, c)

    if mode == "nearest":
        out = sample(jnp.round(ix), jnp.round(iy))
    else:
        x0, y0 = jnp.floor(ix), jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wa = (x1 - ix) * (y1 - iy)
        wb = (x1 - ix) * (iy - y0)
        wc = (ix - x0) * (y1 - iy)
        wd = (ix - x0) * (iy - y0)
        out = (
            sample(x0, y0) * wa[..., None]
            + sample(x0, y1) * wb[..., None]
            + sample(x1, y0) * wc[..., None]
            + sample(x1, y1) * wd[..., None]
        )
    return jnp.transpose(out, (0, 3, 1, 2))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    return _grid_sample_raw(x, grid, mode=mode, padding_mode=padding_mode, align_corners=align_corners)
