"""nn.functional.sparse_attention (reference
``python/paddle/nn/functional/sparse_attention.py`` → CUDA kernel
``operators/sparse_attention_op.cu``: attention restricted to a per-row CSR
pattern over the key positions).

TPU-native: XLA has no scatter-style sparse MMA on the MXU; the efficient
long-context path in this framework is the Pallas flash kernel with
block-skipping (``ops/pallas/flash_attention.py``) and ring attention over
the ``sep`` axis. This op therefore keeps the reference's *semantics* — only
CSR-listed positions participate in the softmax — by materializing the
pattern as an additive mask over score blocks, which XLA fuses into the
attention matmuls. Intended for pattern-parity and moderate sizes, not as
the perf kernel.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op

__all__ = ["sparse_attention"]


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """query/key/value: [B, H, S, D]; offset: [B, H, S+1] int32 CSR row
    offsets; columns: [B, H, NNZ] int32 column indices per row.

    Returns softmax(QK^T/sqrt(D) over the CSR pattern) @ V.
    """

    def fwd(q, k, v, offset, cols, kpm, am):
        b, h, s, d = q.shape
        nnz = cols.shape[-1]
        # CSR -> dense boolean mask [B, H, S, S] without data-dependent
        # shapes: position j participates in row i iff some t in
        # [offset[i], offset[i+1]) has cols[t] == j.
        t_idx = jnp.arange(nnz)[None, None, None, :]                 # [1,1,1,NNZ]
        row_lo = offset[..., :-1, None]                              # [B,H,S,1]
        row_hi = offset[..., 1:, None]                               # [B,H,S,1]
        in_row = (t_idx >= row_lo) & (t_idx < row_hi)                # [B,H,S,NNZ]
        # one-hot of each nonzero's column, masked to its row, or-reduced
        col_oh = jnp.zeros((b, h, s, s), dtype=bool)
        # scatter via take: mask[b,h,i,j] = any(in_row & (cols==j))
        cols_b = cols[..., None, :]                                  # [B,H,1,NNZ]
        j_idx = jnp.arange(s)[None, None, :, None]                   # [1,1,S,1]
        hit = (cols_b == j_idx)                                      # [B,H,S(NNZ j),NNZ]
        # combine: for row i, allowed j iff exists t: in_row[i,t] and cols[t]==j
        allowed = jnp.einsum("bhit,bhjt->bhij", in_row.astype(jnp.float32),
                             hit.astype(jnp.float32)) > 0
        del col_oh
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
        scores = jnp.einsum("bhid,bhjd->bhij", q, k) * scale
        neg = jnp.asarray(jnp.finfo(jnp.float32).min, scores.dtype)
        scores = jnp.where(allowed, scores, neg)
        if kpm is not None:
            scores = jnp.where(kpm[:, None, None, :].astype(bool), scores, neg)
        if am is not None:
            scores = scores + am
        p = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
        p = jnp.where(allowed, p, 0)
        denom = p.sum(axis=-1, keepdims=True)
        p = p / jnp.maximum(denom, jnp.asarray(1e-20, p.dtype))
        return jnp.einsum("bhij,bhjd->bhid", p, v)

    kpm = key_padding_mask if key_padding_mask is not None else None
    am = attn_mask if attn_mask is not None else None
    args = [query, key, value, sparse_csr_offset, sparse_csr_columns]
    args.append(kpm if kpm is not None else jnp.zeros(0))
    args.append(am if am is not None else jnp.zeros(0))

    def fwd_outer(q, k, v, offset, cols, kpm_a, am_a):
        kpm_x = kpm_a if kpm_a.size else None
        am_x = am_a if am_a.size else None
        return fwd(q, k, v, offset, cols, kpm_x, am_x)

    return apply_op("sparse_attention", fwd_outer, tuple(args), {})
