"""Sequence utilities (reference LoD sequence ops are descoped — variable-length
batches are padding+mask based on TPU, see SURVEY.md §7 'Dynamic shapes')."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework.tensor import Tensor


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    lv = lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    m = int(maxlen) if maxlen is not None else int(lv.max())
    mask = jnp.arange(m) < lv[..., None]
    return Tensor(mask.astype(dtypes.convert_dtype(dtype)))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Viterbi decoding of CRF emission potentials (reference
    ``text/viterbi_decode.py`` / ``phi/kernels viterbi_decode``).

    potentials: [B, L, N]; transition_params: [N, N]; lengths: [B].
    With ``include_bos_eos_tag`` the last two tags are BOS/EOS: step 0
    scores add ``trans[BOS, tag]`` and the final step adds
    ``trans[tag, EOS]``. Returns (scores [B], paths [B, L_max])."""
    from ...ops.dispatch import apply_op

    def fwd(pot, trans, lens):
        b, t_max, n = pot.shape
        if include_bos_eos_tag:
            bos, eos = n - 2, n - 1
            # BOS/EOS are virtual: no step may EMIT them
            tag_mask = jnp.full((n,), 0.0).at[bos].set(-1e30).at[eos].set(
                -1e30)
            pot = pot + tag_mask[None, None, :]
            start = pot[:, 0] + trans[bos][None, :]
        else:
            start = pot[:, 0]

        def step(carry, t):
            score, _ = carry
            # score: [B, N]; expand over next tag
            cand = score[:, :, None] + trans[None, :, :] + pot[:, t][:, None, :]
            best_prev = jnp.argmax(cand, axis=1)          # [B, N]
            new_score = jnp.max(cand, axis=1)
            # sequences already ended keep their score frozen
            alive = (t < lens)[:, None]
            new_score = jnp.where(alive, new_score, score)
            return (new_score, t), (best_prev, alive)

        (final_score, _), (backptrs, alives) = jax.lax.scan(
            step, (start, jnp.int32(0)), jnp.arange(1, t_max))
        if include_bos_eos_tag:
            final_score = final_score + trans[:, eos][None, :]
        last_tag = jnp.argmax(final_score, axis=-1)       # [B]
        scores = jnp.max(final_score, axis=-1)

        def back(carry, inp):
            tag = carry
            bp, alive = inp
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            tag_out = jnp.where(alive[:, 0], prev, tag)
            return tag_out, tag

        first, rev_path = jax.lax.scan(back, last_tag, (backptrs, alives),
                                       reverse=True)
        paths = jnp.concatenate([first[None], rev_path], axis=0)
        return scores, jnp.moveaxis(paths, 0, 1).astype(jnp.int64)

    return apply_op("viterbi_decode", fwd,
                    (potentials, transition_params, lengths), {})
