"""Sequence utilities (reference LoD sequence ops are descoped — variable-length
batches are padding+mask based on TPU, see SURVEY.md §7 'Dynamic shapes')."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework.tensor import Tensor


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    lv = lengths._value if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    m = int(maxlen) if maxlen is not None else int(lv.max())
    mask = jnp.arange(m) < lv[..., None]
    return Tensor(mask.astype(dtypes.convert_dtype(dtype)))
