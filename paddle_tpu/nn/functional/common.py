"""Common functionals: linear, dropout, embedding, one_hot, interpolate, etc.
(reference ``python/paddle/nn/functional/common.py``, ``input.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import dtype as dtypes
from ...framework import random as rnd
from ...framework.tensor import Tensor
from ...ops.dispatch import op
from ...ops.manipulation import pad as _pad  # re-export

pad = _pad


@op("linear")
def _linear_raw(x, weight, bias=None):
    # paddle weight layout: [in_features, out_features] (x @ W + b)
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return _linear_raw(x, weight)
    return _linear_raw(x, weight, bias)


@op("dropout_masked")
def _dropout_masked(x, mask, scale=1.0):
    return x * mask * scale


@op("dropout")
def _dropout_static_raw(x, key_data, p=0.5, mshape=None, scale=1.0,
                        seed_offset=0):
    """Static-graph dropout: the mask is drawn INSIDE the op from the
    per-run key the Executor threads through ``__rng_key__`` (folded with a
    per-node offset), so every Executor.run draws fresh randomness — the
    reference draws per-run curand states the same way.  Forward replay and
    the backward's re-replay see the same env key, hence the same mask."""
    key = jax.random.fold_in(jax.random.wrap_key_data(key_data), seed_offset)
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(mshape))
    return x * keep.astype(x.dtype) * scale


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """reference nn/functional/common.py dropout; mask drawn from the global
    generator so it is reproducible and traceable."""
    if isinstance(p, Tensor):
        p = float(p.item())
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1 - p) if p else x
        return x
    if p == 1.0:
        from ...ops import creation

        return creation.zeros_like(x)
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        mshape = [shape[i] if i in [a % len(shape) for a in axes] else 1 for i in range(len(shape))]
    else:
        mshape = shape
    scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0

    from ...static.program import Variable, default_main_program, in_static_build

    if in_static_build() and isinstance(x, Variable):
        prog = default_main_program()
        return _dropout_static_raw(x, prog.rng_var(), p=float(p),
                                   mshape=tuple(mshape), scale=scale,
                                   seed_offset=prog.next_rng_offset())

    keep = jax.random.bernoulli(rnd.next_key(), 1.0 - p, tuple(mshape))
    mask = Tensor(keep.astype(x._value.dtype))
    return _dropout_masked(x, mask, scale=scale)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(rnd.next_key(), 1.0 - p, tuple(x.shape))
    a = (1.0 / ((1 - p) * (1 + p * alpha_p**2)) ** 0.5)
    b = -a * alpha_p * p
    return _alpha_dropout_masked(x, Tensor(keep.astype(x._value.dtype)), alpha_p=alpha_p, a=a, b=b)


@op("alpha_dropout_masked")
def _alpha_dropout_masked(x, mask, alpha_p=0.0, a=1.0, b=0.0):
    return (x * mask + alpha_p * (1 - mask)) * a + b


@op("embedding_op")
def _embedding_raw(weight, ids, padding_idx=None):
    ids = ids.astype(jnp.int32)
    out = jnp.take(weight, ids, axis=0)
    if padding_idx is not None:
        # paddle accepts padding_idx in [-vocab, vocab)
        pi = padding_idx if padding_idx >= 0 else padding_idx + weight.shape[0]
        mask = (ids != pi)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    # the int32 cast happens INSIDE the recorded op so static Variables
    # stay symbolic (no eager ._value access at record time)
    if sparse:
        out = _embedding_sparse(x, weight, padding_idx)
        if out is not None:
            return out
    return _embedding_raw(weight, x, padding_idx=padding_idx)


def _embedding_sparse(x, weight, padding_idx):
    """Row-sparse gradient path (reference ``Embedding(sparse=True)`` →
    SelectedRows grad, ``phi/core/selected_rows.h``): the backward emits a
    (rows=ids, values=cotangent) SelectedRows instead of a dense scatter
    onto the whole table. Eager leaf-weight path only; static recording or
    a non-leaf weight falls back to the dense op (returns None)."""
    from ...autograd.engine import GradNode, is_grad_enabled, leaf_edge
    from ...framework.selected_rows import SelectedRows
    from ...ops import dispatch

    if dispatch.STATIC_RECORDER is not None or not is_grad_enabled():
        return None
    if weight.stop_gradient or weight._grad_node is not None:
        return None
    ids = x._value.astype(jnp.int32)
    w = weight._value
    out_val = jnp.take(w, ids, axis=0)
    pi = None
    if padding_idx is not None:
        pi = padding_idx if padding_idx >= 0 else padding_idx + w.shape[0]
        out_val = out_val * (ids != pi)[..., None].astype(out_val.dtype)
    height, dim = w.shape[0], w.shape[1]
    flat_ids = ids.reshape(-1)

    def vjp_fn(cot):
        vals = cot.reshape(-1, dim)
        if pi is not None:
            vals = vals * (flat_ids != pi)[:, None].astype(vals.dtype)
        return (SelectedRows(flat_ids, vals, height),)

    node = GradNode("embedding_sparse", vjp_fn, [leaf_edge(weight)],
                    [(out_val.shape, out_val.dtype)], multi=False)
    out = Tensor(out_val, stop_gradient=False)
    out._grad_node = node
    out._out_slot = 0
    return out


def one_hot(x, num_classes, name=None):
    return Tensor(jax.nn.one_hot(x._value, num_classes, dtype=dtypes.get_default_dtype()))


@op("label_smooth_op")
def _label_smooth_raw(label, prior=None, epsilon=0.1):
    n = label.shape[-1]
    if prior is None:
        return (1 - epsilon) * label + epsilon / n
    return (1 - epsilon) * label + epsilon * prior


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is None:
        return _label_smooth_raw(label, epsilon=epsilon)
    return _label_smooth_raw(label, prior_dist, epsilon=epsilon)


# ------------------------------------------------------------ interpolate ---


@op("interp_op")
def _interpolate_raw(x, size=None, mode="nearest", align_corners=False, data_format="NCHW"):
    # normalize to NHWC-ish for jax.image
    chan_last = data_format.endswith("C")
    if not chan_last:
        perm = [0] + list(range(2, x.ndim)) + [1]
        x = jnp.transpose(x, perm)
    spatial = x.shape[1:-1]
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "trilinear": "linear",
        "linear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]
    out_shape = (x.shape[0], *size, x.shape[-1])
    y = jax.image.resize(x, out_shape, method=method)
    if not chan_last:
        inv = [0, x.ndim - 1] + list(range(1, x.ndim - 1))
        y = jnp.transpose(y, inv)
    return y


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format=None,
    name=None,
):
    nd = x.ndim - 2
    if data_format is None:
        data_format = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nd]
    chan_last = data_format.endswith("C")
    spatial = x.shape[1:-1] if chan_last else x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy()]
        size = [int(v.item()) if isinstance(v, Tensor) else int(v) for v in (size if isinstance(size, (list, tuple)) else [size] * nd)]
    return _interpolate_raw(x, size=tuple(size), mode=mode, align_corners=align_corners, data_format=data_format)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


@op("pixel_shuffle_op")
def _pixel_shuffle_raw(x, upscale_factor=1, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return _pixel_shuffle_raw(x, upscale_factor=upscale_factor, data_format=data_format)


@op("pixel_unshuffle_op")
def _pixel_unshuffle_raw(x, downscale_factor=1, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4)).reshape(n, h // r, w // r, c * r * r)
    return x


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return _pixel_unshuffle_raw(x, downscale_factor=downscale_factor, data_format=data_format)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return _channel_shuffle_raw(x, groups=groups, data_format=data_format)


@op("channel_shuffle_op")
def _channel_shuffle_raw(x, groups=1, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.swapaxes(x, 1, 2)
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


@op("unfold_op")
def _unfold_raw(x, kernel_sizes=(), strides=(), paddings=(), dilations=()):
    n, c, h, w = x.shape
    kh, kw = kernel_sizes
    sh, sw = strides
    ph0, pw0, ph1, pw1 = paddings[0], paddings[1], paddings[2], paddings[3]
    dh, dw = dilations
    x = jnp.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(
                x[:, :, i * dh : i * dh + oh * sh : sh, j * dw : j * dw + ow * sw : sw]
            )
    out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
    return out.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    ks, st, dl = pair(kernel_sizes), pair(strides), pair(dilations)
    pd = paddings
    if isinstance(pd, int):
        pd = [pd, pd, pd, pd]
    elif len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    return _unfold_raw(x, kernel_sizes=tuple(ks), strides=tuple(st), paddings=tuple(pd), dilations=tuple(dl))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    os_, ks, st, dl = pair(output_sizes), pair(kernel_sizes), pair(strides), pair(dilations)
    pd = paddings
    if isinstance(pd, int):
        pd = [pd, pd, pd, pd]
    elif len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    return _fold_raw(x, output_sizes=tuple(os_), kernel_sizes=tuple(ks), strides=tuple(st), paddings=tuple(pd), dilations=tuple(dl))


@op("fold_op")
def _fold_raw(x, output_sizes=(), kernel_sizes=(), strides=(), paddings=(), dilations=()):
    n, ckk, L = x.shape
    kh, kw = kernel_sizes
    c = ckk // (kh * kw)
    oh_p = output_sizes[0] + paddings[0] + paddings[2]
    ow_p = output_sizes[1] + paddings[1] + paddings[3]
    sh, sw = strides
    dh, dw = dilations
    nh = (oh_p - (dh * (kh - 1) + 1)) // sh + 1
    nw = (ow_p - (dw * (kw - 1) + 1)) // sw + 1
    xr = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh_p, ow_p), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh : i * dh + nh * sh : sh, j * dw : j * dw + nw * sw : sw].add(
                xr[:, :, i, j]
            )
    return out[:, :, paddings[0] : oh_p - paddings[2], paddings[1] : ow_p - paddings[3]]


@op("cosine_similarity_op")
def _cosine_similarity_raw(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    return _cosine_similarity_raw(x1, x2, axis=axis, eps=eps)


@op("bilinear_op")
def _bilinear_raw(x1, x2, weight, bias=None):
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


def bilinear(x1, x2, weight, bias=None, name=None):
    if bias is None:
        return _bilinear_raw(x1, x2, weight)
    return _bilinear_raw(x1, x2, weight, bias)


# -- round-4 API-audit additions --------------------------------------------

@op("diag_embed")
def _diag_embed_raw(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1]
    size = n + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (size, size), x.dtype)
    rows = jnp.arange(n) + max(-offset, 0)
    cols = jnp.arange(n) + max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
    # place the new square dims at (dim1, dim2)
    order = []
    src = {d1: nd - 2, d2: nd - 1}
    it = iter(perm)
    for i in range(nd):
        order.append(src[i] if i in src else next(it))
    return jnp.transpose(out, order)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched vectors -> matrices with the vector on the (offset) diagonal
    (reference ``nn/functional/extension.py:34``)."""
    return _diag_embed_raw(input, offset=int(offset), dim1=int(dim1),
                           dim2=int(dim2))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad a 4-D tensor's spatial dims with (left, right, top, bottom)
    (reference ``nn/functional/common.py:1541``)."""
    if isinstance(padding, Tensor):
        padding = [int(v) for v in padding.numpy()]
    l, r, t, b = (int(p) for p in padding)
    if data_format == "NCHW":
        widths = [(0, 0), (0, 0), (t, b), (l, r)]
    else:
        widths = [(0, 0), (t, b), (l, r), (0, 0)]
    from ...ops.dispatch import apply_op

    return apply_op("zeropad2d", lambda v: jnp.pad(v, widths), (x,), {})


@op("temporal_shift")
def _temporal_shift_raw(x, seg_num=1, shift_ratio=0.25, channel_last=False):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    n = nt // seg_num
    xr = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    # segment t takes: first fold channels from t+1 (shift back), next fold
    # from t-1 (shift forward), the rest unshifted (TSM, reference
    # phi/kernels temporal_shift)
    back = jnp.concatenate(
        [xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, fold:2 * fold]), xr[:, :-1, fold:2 * fold]],
        axis=1)
    out = jnp.concatenate([back, fwd, xr[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    return jnp.moveaxis(out, 1, -1) if channel_last else out


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """Temporal Shift Module op (reference
    ``nn/functional/extension.py:328``)."""
    return _temporal_shift_raw(x, seg_num=int(seg_num),
                               shift_ratio=float(shift_ratio),
                               channel_last=(data_format == "NHWC"))


def gather_tree(ids, parents):
    """Walk beam-search parent pointers backward so every step holds the
    full-path token (reference ``nn/functional/extension.py gather_tree``;
    ids/parents: [max_time, batch, beam])."""
    from ...ops.dispatch import apply_op

    def fwd(ids_v, parents_v):
        t_max = ids_v.shape[0]

        def step(beams, t):
            idx = t_max - 1 - t
            gathered = jnp.take_along_axis(ids_v[idx], beams, axis=-1)
            new_beams = jnp.take_along_axis(parents_v[idx], beams, axis=-1)
            return new_beams, gathered

        init = jnp.broadcast_to(
            jnp.arange(ids_v.shape[-1], dtype=ids_v.dtype), ids_v.shape[1:])
        _, rev = jax.lax.scan(step, init, jnp.arange(t_max))
        return rev[::-1]

    return apply_op("gather_tree", fwd, (ids, parents), {})


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample ``num_samples`` class centers always containing the positive
    classes; remap labels into the sampled list (reference
    ``nn/functional/common.py class_center_sample`` — PartialFC). Single
    controller: the whole class range lives here, so the "per-rank class
    section" is the full range."""
    from ...ops.dispatch import apply_nondiff_op

    key = rnd.next_key()

    def fwd(lab):
        pos = jnp.zeros((num_classes,), jnp.bool_).at[lab].set(True)
        # rank positives first (stable), then randomly permuted negatives
        noise = jax.random.uniform(key, (num_classes,))
        order = jnp.argsort(jnp.where(pos, -1.0, noise))
        sampled = jnp.sort(order[:num_samples])
        # remap: position of each label inside `sampled` (present for all
        # positives as long as num_samples >= #unique positives)
        remap = jnp.zeros((num_classes,), lab.dtype).at[sampled].set(
            jnp.arange(num_samples, dtype=lab.dtype))
        return remap[lab], sampled

    return apply_nondiff_op("class_center_sample", fwd, (label,))
