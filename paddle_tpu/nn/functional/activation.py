"""Activation functionals (reference ``python/paddle/nn/functional/activation.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import op
from ...framework.tensor import Tensor

relu = op("relu")(lambda x: jnp.maximum(x, 0))
relu6 = op("relu6")(lambda x: jnp.clip(x, 0, 6))
sigmoid = op("sigmoid")(lambda x: jax.nn.sigmoid(x))
tanh = op("tanh_act")(lambda x: jnp.tanh(x))
silu = op("silu")(lambda x: jax.nn.silu(x))
swish = silu
mish = op("mish")(lambda x: x * jnp.tanh(jax.nn.softplus(x)))
tanhshrink = op("tanhshrink")(lambda x: x - jnp.tanh(x))


@op("gelu")
def _gelu_raw(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu_raw(x, approximate=approximate)


@op("leaky_relu")
def _leaky_relu_raw(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu_raw(x, negative_slope=negative_slope)


@op("elu")
def _elu_raw(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


def elu(x, alpha=1.0, name=None):
    return _elu_raw(x, alpha=alpha)


def elu_(x, alpha=1.0, name=None):
    return x._rebind(_elu_raw(x, alpha=alpha))


@op("celu")
def _celu_raw(x, alpha=1.0):
    return jax.nn.celu(x, alpha=alpha)


def celu(x, alpha=1.0, name=None):
    return _celu_raw(x, alpha=alpha)


@op("selu")
def _selu_raw(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu_raw(x, scale=scale, alpha=alpha)


@op("hardshrink")
def _hardshrink_raw(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink_raw(x, threshold=threshold)


@op("softshrink")
def _softshrink_raw(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink_raw(x, threshold=threshold)


@op("hardtanh")
def _hardtanh_raw(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return _hardtanh_raw(x, min=min, max=max)


@op("hardsigmoid")
def _hardsigmoid_raw(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return _hardsigmoid_raw(x, slope=slope, offset=offset)


@op("hardswish")
def hardswish(x, name=None):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@op("softplus_op")
def _softplus_raw(x, beta=1.0, threshold=20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.log1p(jnp.exp(jnp.minimum(bx, threshold))) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return _softplus_raw(x, beta=beta, threshold=threshold)


@op("softsign")
def softsign(x, name=None):
    return x / (1.0 + jnp.abs(x))


@op("logsigmoid")
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@op("softmax_op")
def _softmax_raw(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return _softmax_raw(x, axis=int(axis))


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._rebind(softmax(x, axis, dtype))


@op("log_softmax_op")
def _log_softmax_raw(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtype)
    return _log_softmax_raw(x, axis=int(axis))


@op("gumbel_softmax_op")
def _gumbel_softmax_raw(x, g, temperature=1.0, axis=-1):
    return jax.nn.softmax((x + g) / temperature, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as rnd

    g = jax.random.gumbel(rnd.next_key(), tuple(x.shape), x._value.dtype)
    y = _gumbel_softmax_raw(x, Tensor(g), temperature=temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y._value, axis=axis, keepdims=True)
        onehot = jnp.zeros_like(y._value)
        onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
        # straight-through estimator
        from ...ops import math as m

        return m.add(Tensor(onehot - jax.lax.stop_gradient(y._value)), y)
    return y


@op("maxout_op")
def _maxout_raw(x, groups=2, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    new_shape = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return _maxout_raw(x, groups=groups, axis=axis)


@op("thresholded_relu_op")
def _thresholded_relu_raw(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return _thresholded_relu_raw(x, threshold=threshold, value=value)


@op("prelu_op")
def _prelu_raw(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        c_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[c_axis] = weight.size
        w = weight.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return _prelu_raw(x, weight, data_format=data_format)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        from ...framework import random as rnd

        a = jax.random.uniform(rnd.next_key(), tuple(x.shape), x._value.dtype, lower, upper)
        return _prelu_like(x, Tensor(a))
    return _leaky_relu_raw(x, negative_slope=(lower + upper) / 2.0)


@op("rrelu_train")
def _prelu_like(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


def relu_(x, name=None):
    return x._rebind(relu(x))


def glu(x, axis=-1, name=None):
    return _glu_raw(x, axis=axis)


@op("glu_op")
def _glu_raw(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)
