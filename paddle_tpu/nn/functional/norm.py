"""Normalization functionals (reference ``python/paddle/nn/functional/norm.py``;
CUDA kernels ``paddle/phi/kernels/gpu/batch_norm_kernel.cu``, layer_norm etc.).
XLA fuses these elementwise chains; a fused Pallas layer_norm lives in
``paddle_tpu.ops.pallas`` and is used automatically on TPU for large widths."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import op


@op("layer_norm_op")
def _layer_norm_raw(x, weight=None, bias=None, epsilon=1e-5, begin_axis=-1, has_w=False, has_b=False):
    # fp32 statistics and x.dtype output regardless of path or weight dtype
    # (matches the fused Pallas kernel and the reference CUDA layer_norm,
    # which computes in fp32 and writes back the input dtype)
    xf = x.astype(jnp.float32)
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax_rsqrt(var + epsilon)
    if has_w:
        out = out * weight.astype(jnp.float32)
    if has_b:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def jax_rsqrt(v):
    from jax import lax

    return lax.rsqrt(v)


@op("fused_layer_norm")
def _layer_norm_pallas(x, weight, bias, epsilon=1e-5):
    from ...ops.pallas import fused_layer_norm

    return fused_layer_norm(x, weight, bias, eps=epsilon)


def _pallas_ln_ok(normalized_shape, weight, bias):
    """Fused Pallas LN: TPU backend, last-axis norm, affine, lane-aligned."""
    from ...ops import pallas
    from ...ops.pallas.layer_norm import supports

    return (
        len(normalized_shape) == 1
        and weight is not None
        and bias is not None
        and supports(normalized_shape[0])
        and pallas.is_available()
    )


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    if _pallas_ln_ok(normalized_shape, weight, bias):
        return _layer_norm_pallas(x, weight, bias, epsilon=epsilon)
    begin = x.ndim - len(normalized_shape)
    args = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        args.append(weight)
    if has_b:
        if not has_w:
            # keep positional protocol: weight slot must be filled
            from ...ops import creation

            args.append(creation.ones(normalized_shape, x.dtype))
            has_w = True
        args.append(bias)
    return _layer_norm_raw(*args, epsilon=epsilon, begin_axis=begin, has_w=has_w, has_b=has_b)


@op("batch_norm_infer")
def _bn_infer_raw(x, rm, rv, weight, bias, epsilon=1e-5, axis=1):
    # mixed-precision contract (same as the pallas layer_norm): statistics
    # and the affine math run in fp32, the output returns in x.dtype — a
    # bf16 conv stack with fp32 BN params stays bf16 end-to-end
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    f32 = jnp.float32
    scale = weight.astype(f32).reshape(shape) * jax_rsqrt(
        rv.astype(f32).reshape(shape) + epsilon)
    out = x.astype(f32) * scale + (
        bias.astype(f32).reshape(shape) - rm.astype(f32).reshape(shape) * scale)
    return out.astype(x.dtype)


@op("batch_norm_train")
def _bn_train_raw(x, weight, bias, epsilon=1e-5, axis=1):
    # fp32 statistics via one explicit upcast. Alternatives measured on
    # ResNet-50 b128/v5e: per-consumer inline casts with the E[x^2]-E[x]^2
    # variance collapsed throughput 14x (XLA fusion cliff), so the shared
    # xf copy stays — its convert_reduce cost (~38% of a BN-heavy step) is
    # the price of usable bf16 BN gradients.
    axes = tuple(i for i in range(x.ndim) if i != axis)
    f32 = jnp.float32
    xf = x.astype(f32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    scale = weight.astype(f32).reshape(shape) * jax_rsqrt(
        var.reshape(shape) + epsilon)
    out = xf * scale + (
        bias.astype(f32).reshape(shape) - mean.reshape(shape) * scale)
    return out.astype(x.dtype), mean, var


def batch_norm(
    x,
    running_mean,
    running_var,
    weight,
    bias,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """reference nn/functional/norm.py batch_norm. Running stats are updated
    in-place on the provided tensors (functional rebind), matching paddle's
    mutable running_mean/var semantics."""
    axis = x.ndim - 1 if data_format.endswith("C") and x.ndim > 2 and data_format != "NCHW" else 1
    if data_format in ("NHWC", "NLC", "NDHWC"):
        axis = x.ndim - 1
    use_stats = use_global_stats if use_global_stats is not None else not training
    if use_stats:
        return _bn_infer_raw(x, running_mean, running_var, weight, bias, epsilon=epsilon, axis=axis)
    out, mean, var = _bn_train_raw(x, weight, bias, epsilon=epsilon, axis=axis)
    # update running stats (no grad flows; detached values)
    m = momentum
    n = x.size // x.shape[axis]
    # _bn_train_raw returns fp32 stats; cast the update back so bf16
    # running buffers keep their declared dtype across training steps
    unbiased = var._value * (n / max(n - 1, 1))
    rm_dt = running_mean._value.dtype
    rv_dt = running_var._value.dtype
    running_mean._value = (running_mean._value * m
                           + mean._value.astype(rm_dt) * (1 - m)).astype(rm_dt)
    running_var._value = (running_var._value * m
                          + unbiased.astype(rv_dt) * (1 - m)).astype(rv_dt)
    return out


@op("instance_norm_op")
def _instance_norm_raw(x, weight=None, bias=None, epsilon=1e-5, has_affine=False):
    # fp32-internal like batch_norm: normalization in low precision loses
    # the mean-subtraction cancellation; output returns in x.dtype
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax_rsqrt(var + epsilon)
    if has_affine:
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        out = (out * weight.astype(jnp.float32).reshape(shape)
               + bias.astype(jnp.float32).reshape(shape))
    return out.astype(x.dtype)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    if weight is not None and bias is not None:
        return _instance_norm_raw(x, weight, bias, epsilon=eps, has_affine=True)
    return _instance_norm_raw(x, epsilon=eps, has_affine=False)


@op("group_norm_op")
def _group_norm_raw(x, weight=None, bias=None, epsilon=1e-5, groups=1, has_affine=False, channel_last=False):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    xg = x.astype(jnp.float32).reshape(n, groups, c // groups, *spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax_rsqrt(var + epsilon)).reshape(n, c, *spatial)
    if has_affine:
        shape = [1, c] + [1] * len(spatial)
        out = (out * weight.astype(jnp.float32).reshape(shape)
               + bias.astype(jnp.float32).reshape(shape))
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out.astype(x.dtype)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    has_affine = weight is not None
    args = [x]
    if has_affine:
        args += [weight, bias]
    return _group_norm_raw(*args, epsilon=epsilon, groups=num_groups, has_affine=has_affine, channel_last=data_format.endswith("C") and data_format != "NCHW")


@op("normalize_op")
def _normalize_raw(x, p=2.0, axis=1, epsilon=1e-12):
    norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _normalize_raw(x, p=float(p), axis=axis, epsilon=epsilon)


@op("local_response_norm_op")
def _lrn_raw(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    # NCHW: normalize across channel windows
    sq = jnp.square(x)
    c = x.shape[1]
    half = size // 2
    padded = jnp.pad(sq, ((0, 0), (half, size - half - 1), (0, 0), (0, 0)))
    acc = sum(padded[:, i : i + c] for i in range(size))
    return x / ((k + alpha * acc) ** beta)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    if data_format != "NCHW":
        from ...ops import manipulation as man

        x = man.transpose(x, [0, 3, 1, 2])
        out = _lrn_raw(x, size=size, alpha=alpha, beta=beta, k=k)
        return man.transpose(out, [0, 2, 3, 1])
    return _lrn_raw(x, size=size, alpha=alpha, beta=beta, k=k)


@op("spectral_norm_op")
def _spectral_norm_apply(weight, u, v, dim=0, power_iters=1, eps=1e-12):
    w = jnp.moveaxis(weight, dim, 0)
    wm = w.reshape(w.shape[0], -1)
    for _ in range(power_iters):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ (wm @ v)
    return weight / sigma


def spectral_norm(x, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12, name=None):
    return _spectral_norm_apply(x, weight_u, weight_v, dim=dim, power_iters=power_iters, eps=eps)
