"""paddle.nn.utils (reference ``python/paddle/nn/utils/``: weight_norm /
spectral_norm reparameterization hooks + parameter<->vector transforms)."""
from .weight_norm_hook import remove_weight_norm, weight_norm  # noqa: F401
from .spectral_norm_hook import spectral_norm  # noqa: F401
from .transform_parameters import (  # noqa: F401
    parameters_to_vector,
    vector_to_parameters,
)
