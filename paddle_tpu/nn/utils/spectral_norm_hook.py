"""Spectral normalization hook (reference
``nn/utils/spectral_norm_hook.py``): ``w = w_orig / sigma(w)`` with sigma
estimated by power iteration, updated each forward.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Parameter, Tensor

__all__ = ["spectral_norm"]


class _SpectralNormHook:
    def __init__(self, name, n_power_iterations, eps, dim):
        self.name = name
        self.n = n_power_iterations
        self.eps = eps
        self.dim = dim

    def _reshape(self, w):
        if self.dim != 0:
            perm = [self.dim] + [i for i in range(w.ndim) if i != self.dim]
            w = jnp.transpose(w, perm)
        return w.reshape(w.shape[0], -1)

    def __call__(self, layer, inputs):
        w = getattr(layer, self.name + "_orig")
        u = getattr(layer, self.name + "_u")
        from ...ops.dispatch import apply_op

        n, eps, dim = self.n, self.eps, self.dim
        reshape = self._reshape

        def fwd(w_val, u_val):
            wm = reshape(w_val.astype(jnp.float32))
            uu = u_val.astype(jnp.float32)
            vv = None
            for _ in range(max(n, 1)):
                vv = wm.T @ uu
                vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
                uu = wm @ vv
                uu = uu / jnp.maximum(jnp.linalg.norm(uu), eps)
            sigma = uu @ wm @ vv
            return (w_val.astype(jnp.float32) / sigma).astype(w_val.dtype), uu

        out = apply_op("spectral_norm_hook", fwd, (w, u), {})
        w_n, u_new = out
        tgt = getattr(layer, self.name)
        tgt._value = w_n._value
        tgt._grad_node = w_n._grad_node
        tgt._out_slot = w_n._out_slot
        u._value = u_new._value  # power-iteration state (no grad)
        return None


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    if hasattr(layer, name + "_orig"):
        raise ValueError(f"spectral_norm already applied to {name!r}")
    w = getattr(layer, name)
    if dim is None:
        dim = 1 if type(layer).__name__ in ("Linear",) else 0
    rng = np.random.RandomState(0)
    u0 = rng.randn(w._value.shape[dim]).astype(np.float32)
    u0 /= max(np.linalg.norm(u0), eps)

    orig = Parameter(jnp.asarray(w._value))
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]
    derived = Parameter(jnp.asarray(w._value))
    object.__setattr__(layer, name, derived)
    u = Tensor(jnp.asarray(u0))
    u.stop_gradient = True
    layer.register_buffer(name + "_u", u)
    hook = _SpectralNormHook(name, n_power_iterations, eps, dim)
    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_hooks = getattr(layer, "_spectral_norm_hooks", {})
    layer._spectral_norm_hooks[name] = (hook, handle)
    hook(layer, ())
    return layer
