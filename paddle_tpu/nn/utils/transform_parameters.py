"""Parameter <-> flat-vector transforms (reference
``nn/utils/transform_parameters.py:74,121``); used by L-BFGS-style
optimizers and parameter averaging."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor

__all__ = ["parameters_to_vector", "vector_to_parameters"]


def parameters_to_vector(parameters, name=None):
    vals = [p._value.reshape(-1) for p in parameters]
    if not vals:
        raise ValueError("parameters_to_vector got an empty parameter list")
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    offset = 0
    total = sum(int(jnp.size(p._value)) for p in parameters)
    if int(v.size) != total:
        raise ValueError(
            f"vector has {int(v.size)} elements but parameters need {total}")
    for p in parameters:
        n = int(jnp.size(p._value))
        p._value = v[offset:offset + n].reshape(p._value.shape).astype(
            p._value.dtype)
        offset += n
