"""Weight normalization reparameterization (reference
``nn/utils/weight_norm_hook.py``): ``w = g * v / ||v||`` with ``g``/``v``
trainable and ``w`` recomputed by a forward pre-hook each call.

TPU-native note: the recompute is a tiny normalized-scale expression XLA
fuses into the consuming matmul; under CompiledStep the hook runs inside
the trace so the reparameterization compiles into the step.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except_dim(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def _compute_weight(g, v, dim):
    vv = v._value.astype(jnp.float32)
    norm = _norm_except_dim(vv, dim)
    w = (g._value.astype(jnp.float32) * vv / jnp.maximum(norm, 1e-12))
    return w.astype(v._value.dtype)


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        w = getattr(layer, self.name)
        # recompute w = g * v/||v|| as a recorded op so gradients flow to
        # (g, v) through whatever consumes w this forward
        from ...ops.dispatch import apply_op

        dim = self.dim
        out = apply_op("weight_norm_recompute",
                       lambda gv, vv: _compute_weight_raw(gv, vv, dim),
                       (g, v), {})
        w._value = out._value
        w._grad_node = out._grad_node
        w._out_slot = out._out_slot
        return None


def _compute_weight_raw(g, v, dim):
    vv = v.astype(jnp.float32)
    norm = _norm_except_dim(vv, dim)
    return (g.astype(jnp.float32) * vv / jnp.maximum(norm, 1e-12)).astype(v.dtype)


def weight_norm(layer, name="weight", dim=0):
    """Replace ``layer.<name>`` with the (g, v) parameterization."""
    if hasattr(layer, name + "_g"):
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    dim_ = dim if dim is not None else None
    vv = w._value
    norm = _norm_except_dim(vv.astype(jnp.float32), dim_)
    g = Parameter(jnp.asarray(norm, jnp.float32))
    v = Parameter(jnp.asarray(vv))
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # demote the original weight to a derived (non-trainable-leaf) tensor:
    # it stays an attribute so forward() code is unchanged, but the
    # parameter list exposes only g and v
    del layer._parameters[name]
    derived = Parameter(jnp.asarray(vv))
    derived.stop_gradient = False
    object.__setattr__(layer, name, derived)
    hook = _WeightNormHook(name, dim_)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    # initialize w once so inference-before-first-forward also works
    hook(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    hook, handle = hooks.pop(name)
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = Parameter(jnp.asarray(_compute_weight(g, v, hook.dim)))
    handle.remove() if hasattr(handle, "remove") else None
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    delattr(layer, name + "_g") if hasattr(type(layer), name + "_g") else None
    layer.add_parameter(name, w)
    return layer
