"""paddle.nn.quant — quantization-aware training layers.

Reference: ``python/paddle/nn/quant/quant_layers.py`` (FakeQuantAbsMax,
FakeQuantMovingAverageAbsMax, QuantizedLinear/QuantizedConv2D) backed by the
``fake_quantize_*`` CUDA kernels. TPU-native: quant-dequant is a traced
round/clip with a straight-through-estimator custom VJP — one fused XLA
elementwise chain — and the observers' moving state lives as layer buffers
so QAT jit-compiles with the rest of the step.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import op
from ..layer.layers import Layer
from ..layer.common import Linear
from ..layer.conv import Conv2D

__all__ = [
    "FakeQuantAbsMax",
    "FakeQuantMovingAverageAbsMax",
    "QuantizedLinear",
    "QuantizedConv2D",
    "quant_aware",
]


@jax.custom_vjp
def _quant_dequant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _qd_fwd(x, scale, qmax):
    return _quant_dequant(x, scale, qmax), (x, scale, qmax)


def _qd_bwd(res, g):
    x, scale, qmax = res
    # straight-through estimator, gated to the clip range
    inside = (jnp.abs(x) <= jnp.maximum(scale, 1e-8)).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale), None


_quant_dequant.defvjp(_qd_fwd, _qd_bwd)


@op("fake_quant_abs_max")
def _fake_quant_abs_max(x, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    return _quant_dequant(x, scale, qmax)


@op("fake_quant_moving_abs_max")
def _fake_quant_moving(x, state, rate=0.9, bits=8, training=True):
    """state: [accum, scale]; returns (out, new_state)."""
    qmax = float(2 ** (bits - 1) - 1)
    cur = jnp.max(jnp.abs(x))
    accum, scale = state[0], state[1]
    new_scale = jnp.where(training, rate * scale + (1 - rate) * cur, scale)
    out = _quant_dequant(x, jnp.where(training, cur, new_scale), qmax)
    return out, jnp.stack([accum + 1.0, new_scale])


class FakeQuantAbsMax(Layer):
    """Reference ``quant_layers.py FakeQuantAbsMax``: per-tensor abs-max
    quant-dequant with STE gradients."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        return _fake_quant_abs_max(x, bits=self.quant_bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """Reference FakeQuantMovingAverageAbsMax: EMA of the activation range
    (training) frozen at eval."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.state = self.create_parameter([2], default_initializer=None,
                                           is_bias=True)
        self.state.stop_gradient = True
        import numpy as np

        self.state._value = jnp.asarray(np.array([0.0, 1.0], np.float32))

    def forward(self, x):
        out, new_state = _fake_quant_moving(
            x, self.state, rate=self.moving_rate, bits=self.quant_bits,
            training=self.training)
        self.state._value = new_state._value
        return out


class QuantizedLinear(Layer):
    """Reference QuantizedLinear: fake-quant on weight + input."""

    def __init__(self, layer: Linear, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(quant_bits=weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x):
        from .. import functional as F

        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(Layer):
    def __init__(self, layer: Conv2D, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = layer
        self.weight_quant = FakeQuantAbsMax(quant_bits=weight_bits)
        self.act_quant = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x):
        from .. import functional as F

        xq = self.act_quant(x)
        wq = self.weight_quant(self.inner.weight)
        return F.conv2d(xq, wq, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups,
                        data_format=getattr(self.inner, "_data_format",
                                            "NCHW"))


def quant_aware(model, weight_bits=8, activation_bits=8, moving_rate=0.9):
    """Swap every Linear/Conv2D sublayer for its quantized wrapper (the
    QAT model-rewrite the reference's slim tooling performs)."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, Linear):
            model._sub_layers[name] = QuantizedLinear(
                sub, weight_bits, activation_bits, moving_rate)
        elif isinstance(sub, Conv2D):
            model._sub_layers[name] = QuantizedConv2D(
                sub, weight_bits, activation_bits, moving_rate)
        else:
            quant_aware(sub, weight_bits, activation_bits, moving_rate)
    return model
