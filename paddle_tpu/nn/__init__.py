"""paddle.nn equivalent (reference ``python/paddle/nn/__init__.py``)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .layer.layers import Layer, ParamAttr  # noqa: F401
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from . import quant  # noqa: F401

from ..utils.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
