"""Seq2seq decoding: ``BeamSearchDecoder`` + ``dynamic_decode``.

Reference: ``python/paddle/nn/decode.py`` (BeamSearchDecoder over an
RNNCell-like step function; dynamic_decode drives Decoder.initialize/step
until all beams finish, then walks parent pointers with gather_tree).

TPU-native notes: the decode loop is a host loop over jitted steps — the
data-dependent stop condition lives on the host exactly like the
reference's dygraph path (a ``lax.while_loop`` version would forbid the
user-supplied Python cell). States are arbitrary pytrees of Tensors;
beam gathers tree-map over them.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from . import functional as F

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _val(x):
    return x._value if isinstance(x, Tensor) else x


class Decoder:
    """Abstract decode contract (reference ``nn/decode.py Decoder``)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BeamSearchDecoder(Decoder):
    """Beam search over a cell (reference ``nn/decode.py:102``).

    ``cell(inputs, states) -> (logits_or_cell_out, next_states)``;
    ``embedding_fn`` maps token ids to cell inputs; ``output_fn`` maps the
    cell output to vocab logits when the cell itself does not.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] (reference helper)."""
        v = _val(x)
        tiled = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + v.shape[1:]))

    def _merge(self, v):
        return v.reshape((-1,) + v.shape[2:])

    def _split(self, v):
        return v.reshape((self._batch, self.beam_size) + v.shape[1:])

    def initialize(self, inits):
        states = jax.tree_util.tree_map(_val, inits)
        batch = jax.tree_util.tree_leaves(states)[0].shape[0]
        self._batch = batch
        # beam-tile every state leaf
        states = jax.tree_util.tree_map(
            lambda v: jnp.repeat(v[:, None], self.beam_size, axis=1).reshape(
                (-1,) + v.shape[1:]), states)
        ids = jnp.full((batch, self.beam_size), self.start_token, jnp.int64)
        # only beam 0 live initially (identical beams would tie forever)
        log_probs = jnp.tile(
            jnp.array([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32),
            (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), jnp.bool_)
        init = {"states": states, "log_probs": log_probs,
                "finished": finished, "lengths": jnp.zeros(
                    (batch, self.beam_size), jnp.int64)}
        return ids, init, finished

    def step(self, time, inputs, states, **kwargs):
        cell_states = states["states"]
        emb = (self.embedding_fn(Tensor(self._merge(_val(inputs))))
               if self.embedding_fn is not None
               else Tensor(self._merge(_val(inputs))))
        out, next_cell_states = self.cell(emb, jax.tree_util.tree_map(
            Tensor, cell_states), **kwargs)
        logits = self.output_fn(out) if self.output_fn is not None else out
        logits = _val(logits)
        vocab = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = self._split(logp)                     # [batch, beam, vocab]

        finished = states["finished"]
        # finished beams may only emit end_token at zero cost
        fin_mask = jnp.full((vocab,), -1e9, jnp.float32).at[
            self.end_token].set(0.0)
        logp = jnp.where(finished[..., None], fin_mask[None, None], logp)
        total = states["log_probs"][..., None] + logp

        flat = total.reshape(self._batch, -1)
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parents = (top_idx // vocab).astype(jnp.int64)
        tokens = (top_idx % vocab).astype(jnp.int64)

        def gather_beam(v):
            vs = self._split(v)
            idx = parents.reshape(
                (self._batch, self.beam_size) + (1,) * (vs.ndim - 2))
            return jnp.take_along_axis(
                vs, idx, axis=1).reshape((-1,) + vs.shape[2:])

        next_cell_states = jax.tree_util.tree_map(
            lambda t: gather_beam(_val(t)), next_cell_states)
        new_finished = (jnp.take_along_axis(finished, parents, 1)
                        | (tokens == self.end_token))
        lengths = jnp.take_along_axis(states["lengths"], parents, 1)
        lengths = jnp.where(new_finished, lengths, lengths + 1)

        next_states = {"states": next_cell_states, "log_probs": top_scores,
                       "finished": new_finished, "lengths": lengths}
        outputs = {"scores": top_scores, "predicted_ids": tokens,
                   "parent_ids": parents}
        return outputs, next_states, tokens, new_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        ids = jnp.stack([o["predicted_ids"] for o in outputs], 0)
        parents = jnp.stack([o["parent_ids"] for o in outputs], 0)
        walked = F.gather_tree(Tensor(ids), Tensor(parents))
        return walked, final_states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a Decoder until every sequence finishes or ``max_step_num``
    (reference ``nn/decode.py dynamic_decode``)."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    while True:
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outputs.append(out)
        step += 1
        if bool(np.asarray(finished).all()):
            break
        if max_step_num is not None and step >= max_step_num:
            break
    final, final_states = decoder.finalize(outputs, states, None)
    if not output_time_major and isinstance(final, Tensor):
        final = Tensor(jnp.moveaxis(final._value, 0, 1))
    final_states = jax.tree_util.tree_map(
        lambda v: Tensor(v) if not isinstance(v, Tensor) else v,
        final_states)
    if return_length:
        return final, final_states, Tensor(final_states["lengths"]._value
                                           if isinstance(final_states, dict)
                                           else states["lengths"])
    return final, final_states
