"""PyLayer — user-defined dygraph autograd ops.

Reference: ``python/paddle/autograd/py_layer.py:1`` (PyLayer/PyLayerContext,
C++ side ``paddle/fluid/eager/custom_operator`` grad node). TPU-native
redesign: a PyLayer application records a :class:`PyLayerNode` in the same
tape the op dispatcher uses, whose vjp simply *calls the user's* ``backward``
— eagerly (wrapped Tensors) on the raw path, or under grad recording when
``create_graph=True`` so double backward composes through user ops.

The user's forward/backward bodies are ordinary paddle_tpu ops, hence fully
jax-traceable: a PyLayer inside a ``jit.functionalize`` step lowers into the
same single XLA program (the reference's recompute is built on exactly this
property, ``fleet/utils/recompute.py``).
"""
from __future__ import annotations

from .engine import GradNode, is_grad_enabled, leaf_edge, no_grad

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    """Reference ``py_layer.py PyLayerContext``: carries state from forward
    to backward (``save_for_backward``/``saved_tensor``)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace = False
        self.non_differentiable = set()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return list(self._saved)

    def mark_not_inplace(self, *args):
        self.not_inplace = True

    def mark_non_differentiable(self, *tensors):
        for t in tensors:
            self.non_differentiable.add(id(t))

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayerNode(GradNode):
    __slots__ = ("cls", "ctx", "grad_pick")

    def __init__(self, cls, ctx, edges, out_info, multi, grad_pick):
        super().__init__(cls.__name__, None, edges, out_info, multi)
        self.cls = cls
        self.ctx = ctx
        # which of the user-backward's outputs feed our edges (edges only
        # cover the *differentiable* tensor inputs)
        self.grad_pick = grad_pick
        self.vjp_fn = self._raw_vjp

    @property
    def materialize_grads(self):
        return self.ctx.materialize_grads

    def _select(self, grads):
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        if len(grads) < (max(self.grad_pick) + 1 if self.grad_pick else 0):
            raise ValueError(
                f"{self.cls.__name__}.backward returned {len(grads)} gradients "
                f"but the forward had {max(self.grad_pick) + 1}+ Tensor inputs."
            )
        return [grads[i] for i in self.grad_pick]

    def _raw_vjp(self, cots):
        from ..framework.tensor import Tensor

        cot_list = list(cots) if self.multi else [cots]
        tens = [None if c is None else Tensor(c, stop_gradient=True)
                for c in cot_list]
        with no_grad():
            grads = self.cls.backward(self.ctx, *tens)
        picked = self._select(grads)
        return tuple(
            None if g is None else (g._value if isinstance(g, Tensor) else g)
            for g in picked
        )

    def run_vjp_recorded(self, cot_tensors):
        # create_graph path: run the user backward with recording enabled so
        # its ops append to the tape (double backward through PyLayer)
        grads = self.cls.backward(self.ctx, *cot_tensors)
        return tuple(self._select(grads))


class PyLayer:
    """Reference ``python/paddle/autograd/py_layer.py`` PyLayer.

    Subclass with ``forward(ctx, *args)`` / ``backward(ctx, *grads)`` static
    methods and call ``apply``::

        class Scale(PyLayer):
            @staticmethod
            def forward(ctx, x, alpha):
                ctx.save_for_backward(x)
                ctx.alpha = alpha
                return x * alpha

            @staticmethod
            def backward(ctx, dy):
                return dy * ctx.alpha

        y = Scale.apply(x, 2.0)

    ``backward`` must return one gradient per *Tensor* input of forward (None
    allowed); non-Tensor inputs are skipped.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError("You must implement the forward function for PyLayer.")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError("You must implement the backward function for PyLayer.")

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..framework import dtype as dtypes
        from ..framework.tensor import Tensor

        ctx = PyLayerContext()
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)] + [
            v for v in kwargs.values() if isinstance(v, Tensor)
        ]
        diff_inputs, grad_pick = [], []
        for i, t in enumerate(tensor_inputs):
            if (not t.stop_gradient) and dtypes.is_differentiable(t.dtype):
                diff_inputs.append(t)
                grad_pick.append(i)

        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]
        if not (is_grad_enabled() and diff_inputs):
            return outputs

        out_info = [(o._value.shape, o._value.dtype) for o in outs]
        node = PyLayerNode(cls, ctx, [leaf_edge(t) for t in diff_inputs],
                           out_info, multi, grad_pick)
        wrapped = []
        for slot, o in enumerate(outs):
            nd = id(o) in ctx.non_differentiable
            t = Tensor(o._value, stop_gradient=nd)
            if not nd:
                t._grad_node = node
                t._out_slot = slot
            wrapped.append(t)
        return tuple(wrapped) if multi else wrapped[0]
