"""paddle.autograd equivalent."""
from .engine import (  # noqa: F401
    GradNode,
    backward,
    grad,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401


def is_grad_enabled_fn():
    return is_grad_enabled()
