"""paddle.autograd equivalent."""
from .engine import (  # noqa: F401
    GradNode,
    backward,
    grad,
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
)


def is_grad_enabled_fn():
    return is_grad_enabled()
