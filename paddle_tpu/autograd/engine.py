"""Dygraph autograd engine.

TPU-native redesign of the reference eager autograd
(``paddle/fluid/eager/backward.cc:848`` ``egr::Backward`` → ``RunBackward:556``,
node/edge model in ``eager/grad_node_info.h``): each eager op application
records a :class:`GradNode` whose backward function is the ``jax.vjp`` closure
of the op's XLA-lowered forward. ``backward()`` performs the same ready-queue
traversal over the recorded graph, but every backward step is itself a jax
computation — so the *entire* forward+backward+update loop remains traceable by
``jax.jit`` and compiles to one fused XLA program (see paddle_tpu.jit).

Differences from the reference, by design:
 - residual storage & rematerialization are delegated to jax.vjp / jax.checkpoint
   instead of a hand-rolled ``TensorWrapper``;
 - there are no device streams to schedule — XLA handles async execution.
"""
from __future__ import annotations

from collections import deque
from contextlib import contextmanager

import jax.numpy as jnp

__all__ = [
    "GradNode",
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
]

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = bool(mode)
    return prev


class _GradGuard:
    """Context manager *and* decorator, like paddle.no_grad."""

    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn=None):
        if fn is None:
            return _GradGuard(self._mode)
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with _GradGuard(self._mode):
                return fn(*a, **k)

        return wrapper


def no_grad(fn=None):
    g = _GradGuard(False)
    return g(fn) if callable(fn) else g


def enable_grad(fn=None):
    g = _GradGuard(True)
    return g(fn) if callable(fn) else g


class Edge:
    """Where a produced input-cotangent flows (cf. ``egr::Edge``)."""

    __slots__ = ("node", "slot", "leaf")

    def __init__(self, node=None, slot=0, leaf=None):
        self.node = node      # producer GradNode of the input tensor (or None)
        self.slot = slot      # which output slot of that node
        self.leaf = leaf      # leaf Tensor to accumulate .grad into (or None)


def leaf_edge(t) -> Edge:
    """Edge for an op/PyLayer input: to its producer node, or to the leaf."""
    if t._grad_node is not None:
        return Edge(node=t._grad_node, slot=t._out_slot)
    return Edge(leaf=t)


class GradNode:
    """One recorded op application (cf. ``egr::GradNodeBase``)."""

    __slots__ = ("name", "vjp_fn", "edges", "out_info", "multi", "hooks",
                 "fwd_closed", "inputs", "__weakref__")

    def __init__(self, name, vjp_fn, edges, out_info, multi,
                 fwd_closed=None, inputs=None):
        self.name = name
        self.vjp_fn = vjp_fn          # cotangents -> tuple(input cotangents)
        self.edges = edges            # list[Edge], aligned with vjp inputs
        self.out_info = out_info      # list[(shape, dtype)] per output slot
        self.multi = multi            # forward returned a tuple
        self.hooks = {}               # out_slot -> [hook fns]
        # For double backward (create_graph=True): the closed forward over the
        # differentiable primals, and those primal Tensors (≙ the reference's
        # TensorWrapper-saved inputs, eager/tensor_wrapper.h). The backward
        # traversal re-expresses this node's vjp as a *recorded op* over
        # (primals, cotangents), so grad-of-grad flows through both.
        self.fwd_closed = fwd_closed
        self.inputs = inputs

    def run_vjp_recorded(self, cot_tensors):
        """Execute this node's vjp as a recorded op (create_graph path)."""
        import jax

        from ..ops.dispatch import apply_op

        if self.fwd_closed is None or self.inputs is None:
            raise RuntimeError(
                f"GradNode {self.name} does not support create_graph=True "
                "(no saved forward)."
            )
        n_in = len(self.inputs)
        multi = self.multi
        fwd_closed = self.fwd_closed

        def grad_fwd(*vals):
            primals, cots = vals[:n_in], vals[n_in:]
            _, vjp_fn = jax.vjp(fwd_closed, *primals)
            return tuple(vjp_fn(tuple(cots) if multi else cots[0]))

        out = apply_op("grad_" + self.name, grad_fwd,
                       tuple(self.inputs) + tuple(cot_tensors), {})
        return out if isinstance(out, tuple) else (out,)

    def __repr__(self):
        return f"<GradNode {self.name} outs={len(self.out_info)}>"


def _discover(roots):
    """Find reachable nodes and per-node in-degree (count of consumer edges)."""
    indeg = {}
    stack = [n for n in roots if n is not None]
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        indeg.setdefault(id(node), 0)
        for e in node.edges:
            if e.node is not None:
                indeg[id(e.node)] = indeg.get(id(e.node), 0) + 1
                if id(e.node) not in seen:
                    stack.append(e.node)
    return indeg


def _zeros(info):
    shape, dtype = info
    return jnp.zeros(shape, dtype)


def _run(root_pairs, retain_graph=False, accumulate=True, grad_sinks=None,
         create_graph=False):
    """Core traversal. root_pairs: list of (tensor, seed_cotangent).

    If grad_sinks is a dict {id(tensor): tensor}, gradients for those leaves are
    returned in a dict instead of (or in addition to) .grad accumulation.

    With ``create_graph=True`` cotangents flow as *Tensors* and every vjp is
    re-executed through the op dispatcher (``GradNode.run_vjp_recorded``), so
    the produced gradients carry their own grad graph — the reference's
    ``GeneralGrad``/double-backward (``eager/backward.cc:38``).
    """
    from ..framework.tensor import Tensor

    buffers = {}   # id(node) -> list of cotangent per slot
    nodes = {}     # id(node) -> node
    sink_grads = {} if grad_sinks is not None else None
    # For paddle.grad on intermediate (non-leaf) inputs: capture the assembled
    # cotangent at the producing node's slot when that node is processed.
    node_sinks = {}  # (id(node), slot) -> id(tensor)
    if grad_sinks is not None:
        for tid, t in grad_sinks.items():
            if t._grad_node is not None:
                node_sinks[(id(t._grad_node), t._out_slot)] = tid

    root_nodes = []
    for t, seed in root_pairs:
        n = t._grad_node
        if n is None:
            # Leaf root: gradient of itself is the seed.
            _deposit_leaf(t, seed, accumulate, grad_sinks, sink_grads)
            continue
        root_nodes.append(n)
        nodes[id(n)] = n
        buf = buffers.setdefault(id(n), [None] * len(n.out_info))
        s = t._out_slot
        buf[s] = seed if buf[s] is None else buf[s] + seed

    indeg = _discover(root_nodes)
    pending = dict(indeg)
    ready = deque(n for n in {id(r): r for r in root_nodes}.values() if pending.get(id(n), 0) == 0)
    # nodes map fill for traversal
    stack = list(root_nodes)
    while stack:
        n = stack.pop()
        for e in n.edges:
            if e.node is not None and id(e.node) not in nodes:
                nodes[id(e.node)] = e.node
                stack.append(e.node)

    def zeros_for(info):
        z = _zeros(info)
        return Tensor(z, stop_gradient=True) if create_graph else z

    processed = 0
    while ready:
        node = ready.popleft()
        processed += 1
        buf = buffers.get(id(node), [None] * len(node.out_info))
        # PyLayer ctx.set_materialize_grads(False): hand None through instead
        # of zeros (reference py_layer semantics); builtin nodes always
        # materialize (their vjp closures need arrays).
        materialize = getattr(node, "materialize_grads", True)
        cots = [
            b if b is not None else (zeros_for(info) if materialize else None)
            for b, info in zip(buf, node.out_info)
        ]
        if node_sinks:
            for slot in range(len(node.out_info)):
                tid = node_sinks.get((id(node), slot))
                if tid is not None and buf[slot] is not None:
                    sink_grads[tid] = (
                        buf[slot] if tid not in sink_grads else sink_grads[tid] + buf[slot]
                    )
        # per-slot gradient hooks (tensor.register_hook on intermediate tensors)
        for slot, hooks in node.hooks.items():
            for h in hooks:
                arg = cots[slot] if create_graph else Tensor(cots[slot], stop_gradient=True)
                r = h(arg)
                if r is not None:
                    if create_graph:
                        cots[slot] = r
                    else:
                        cots[slot] = r._value if isinstance(r, Tensor) else jnp.asarray(r)
        if create_graph:
            in_cots = node.run_vjp_recorded(cots)
        else:
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"GradNode {node.name} was already released; call backward with "
                    "retain_graph=True to backprop through the same graph twice."
                )
            in_cots = node.vjp_fn(tuple(cots) if node.multi else cots[0])
            if not retain_graph:
                # release residuals AND the saved-for-double-backward primals
                # (else forward activations stay alive through the node chain)
                node.vjp_fn = None
                node.fwd_closed = None
                node.inputs = None
        buffers.pop(id(node), None)
        for e, c in zip(node.edges, in_cots):
            if c is None:
                # a PyLayer backward may return None for an input (no grad)
                if e.node is not None:
                    pending[id(e.node)] -= 1
                    if pending[id(e.node)] == 0:
                        ready.append(e.node)
                continue
            if e.leaf is not None:
                _deposit_leaf(e.leaf, c, accumulate, grad_sinks, sink_grads)
            elif e.node is not None:
                b = buffers.setdefault(id(e.node), [None] * len(e.node.out_info))
                b[e.slot] = c if b[e.slot] is None else b[e.slot] + c
                pending[id(e.node)] -= 1
                if pending[id(e.node)] == 0:
                    ready.append(e.node)
    return sink_grads


def _deposit_leaf(t, cot, accumulate, grad_sinks, sink_grads):
    from ..framework.tensor import Tensor

    is_t = isinstance(cot, Tensor)
    for h in t._hooks:
        r = h(cot if is_t else Tensor(cot, stop_gradient=True))
        if r is not None:
            cot = r if is_t else (r._value if isinstance(r, Tensor) else jnp.asarray(r))
    if grad_sinks is not None:
        # paddle.grad semantics: collect requested grads, never touch .grad.
        if id(t) in grad_sinks:
            sink_grads[id(t)] = (
                cot if id(t) not in sink_grads else sink_grads[id(t)] + cot
            )
        return
    t._accumulate_grad(cot._value if is_t else cot)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward / Tensor.backward.

    Seeds each root with its cotangent (ones for scalar losses) and runs the
    ready-queue traversal, accumulating into leaf ``.grad``.
    """
    from ..framework.tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    pairs = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            continue
        if g is None:
            seed = jnp.ones(t._value.shape, t._value.dtype)
        else:
            seed = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        pairs.append((t, seed))
    _run(pairs, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad — functional gradient w.r.t. ``inputs`` without touching .grad.

    Reference: ``GeneralGrad`` in ``paddle/fluid/eager/backward.cc:38``.
    With ``create_graph=True`` the returned gradients carry their own grad
    graph (vjps re-run through the recording dispatcher), so gradient
    penalties and other higher-order dygraph losses differentiate through.
    """
    from ..framework.tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    retain = bool(retain_graph) if retain_graph is not None else create_graph

    # Temporarily divert leaf deposits for the requested inputs.
    sinks = {id(t): t for t in inputs}
    pairs = []
    for t, g in zip(outputs, grad_outputs):
        if create_graph:
            seed = (
                Tensor(jnp.ones(t._value.shape, t._value.dtype), stop_gradient=True)
                if g is None
                else (g if isinstance(g, Tensor) else Tensor(jnp.asarray(g), stop_gradient=True))
            )
        else:
            seed = (
                jnp.ones(t._value.shape, t._value.dtype)
                if g is None
                else (g._value if isinstance(g, Tensor) else jnp.asarray(g))
            )
        pairs.append((t, seed))
    sink_grads = _run(pairs, retain_graph=retain, accumulate=False,
                      grad_sinks=sinks, create_graph=create_graph)
    results = []
    for t in inputs:
        if id(t) in sink_grads:
            got = sink_grads[id(t)]
            if isinstance(got, Tensor):
                results.append(got)
            else:
                results.append(Tensor(got, stop_gradient=True))
        elif allow_unused:
            results.append(None)
        else:
            raise ValueError(
                "One of the differentiated tensors appears to not have been used "
                "in the graph; set allow_unused=True if this is intended."
            )
    return results
