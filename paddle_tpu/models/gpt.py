"""GPT-family decoder-only transformer — the flagship model.

Reference scale target: the fleet hybrid-parallel trainings the reference is
built for (``fleet/meta_parallel/`` + rank scripts
``unittests/hybrid_parallel_pp_transformer.py``): pre-LN GPT blocks, tied
input/output embeddings, trained under any mix of dp/mp/pp/sharding/sep.

TPU-native design:
  * TP: when the fleet hybrid mesh has mp_degree>1 the QKV/MLP projections
    become Column/RowParallelLinear and the embedding VocabParallelEmbedding
    (weight-sharding annotations; XLA inserts the collectives).
  * PP: ``build_gpt_pipeline_descs`` expresses the same model as
    PipelineLayer descs with tied embeddings via SharedLayerDesc.
  * PP: ``build_pipelined_gpt`` (meta_parallel.pipeline_schedule) runs the
    decoder stack as a jitted SPMD 1F1B pipeline over the ``pp`` axis.
  * Long context: causal sdpa uses the Pallas flash-attention kernel when
    available; past ``blockwise_attention_min_kv`` keys the fallback is
    the blockwise online-softmax KV scan (``functional.attention``,
    ISSUE 15) — O(s·d) live bytes, never the O(s²) einsum score matrix —
    and short sequences keep the fused-einsum XLA path. The serving tier
    reaches the same route by passing ``LengthMask``es; training under an
    HBM budget adds the selective-remat autopilot via
    ``Model.prepare(..., remat=...)`` (``analysis/remat_plan.py``).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ..framework.tensor import Tensor
from .. import ops
from ..utils import warn_once
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer, ParamAttr
from ..nn.layer.norm import LayerNorm

__all__ = [
    "GPTConfig",
    "GPTEmbeddings",
    "GPTDecoderLayer",
    "GPTModel",
    "GPTForCausalLM",
    "build_gpt_pipeline_descs",
]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0  # 0 → 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    use_tp: bool = False       # tensor-parallel projections (needs fleet mp>1)
    use_sep: bool = False      # ring-attention sequence parallelism (sep>1)
    tie_embeddings: bool = True

    @property
    def ffn_size(self):
        return self.intermediate_size or 4 * self.hidden_size


def _mp_degree():
    from ..distributed.fleet.base.fleet_base import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg is not None else 1


def _sep_degree():
    from ..distributed.fleet.base.fleet_base import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    return hcg.get_sep_parallel_world_size() if hcg is not None else 1


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        init = ParamAttr(initializer=Normal(std=cfg.initializer_range))
        if cfg.use_tp and _mp_degree() > 1:
            from ..distributed.meta_parallel import VocabParallelEmbedding

            self.word_embeddings = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=init
            )
        else:
            self.word_embeddings = Embedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=init
            )
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init
        )
        self.dropout = Dropout(cfg.hidden_dropout, mode="upscale_in_train")

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            seq = input_ids.shape[1]
            position_ids = Tensor(
                np.arange(seq, dtype=np.int64)[None, :].repeat(input_ids.shape[0], 0)
            )
        h = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        return self.dropout(h)


class GPTDecoderLayer(Layer):
    """Pre-LN causal block: LN → attn → +res → LN → MLP(gelu) → +res."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h, nh = cfg.hidden_size, cfg.num_heads
        self.head_dim = h // nh
        init = ParamAttr(initializer=Normal(std=cfg.initializer_range))
        out_init = ParamAttr(
            initializer=Normal(std=cfg.initializer_range / math.sqrt(2 * cfg.num_layers))
        )
        tp = cfg.use_tp and _mp_degree() > 1
        if tp:
            from ..distributed.meta_parallel import (
                ColumnParallelLinear,
                RowParallelLinear,
            )

            self.qkv_proj = ColumnParallelLinear(h, 3 * h, weight_attr=init, gather_output=False)
            self.out_proj = RowParallelLinear(h, h, weight_attr=out_init, input_is_parallel=True)
            self.up_proj = ColumnParallelLinear(h, cfg.ffn_size, weight_attr=init, gather_output=False)
            self.down_proj = RowParallelLinear(cfg.ffn_size, h, weight_attr=out_init, input_is_parallel=True)
        else:
            self.qkv_proj = Linear(h, 3 * h, weight_attr=init)
            self.out_proj = Linear(h, h, weight_attr=out_init)
            self.up_proj = Linear(h, cfg.ffn_size, weight_attr=init)
            self.down_proj = Linear(cfg.ffn_size, h, weight_attr=out_init)
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.attn_dropout = cfg.attention_dropout
        self.resid_dropout = Dropout(cfg.hidden_dropout, mode="upscale_in_train")
        self.num_heads = nh
        # ring-attention sequence parallelism over the sep mesh axis
        # (distributed/meta_parallel/sequence_parallel.py — green-field,
        # SURVEY §5; the reference has no SP/CP path)
        self._use_sep = cfg.use_sep and _sep_degree() > 1

    def forward(self, x, attn_mask=None, cache=None):
        b, s, h = x.shape
        residual = x
        y = self.ln_1(x)
        qkv = self.qkv_proj(y)
        # local head count follows the (possibly mp-sharded) projection width
        local_width = qkv.shape[-1] // 3
        nh_local = max(1, self.num_heads * local_width // h)
        qkv = qkv.reshape([b, s, 3, nh_local, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cache is not None:
            if isinstance(cache, (tuple, list)):
                # DEPRECATED grow-by-concat path: every step changes the
                # cache operand shape (one XLA executable per position — the
                # analysis `kv-cache-concat` rule flags exactly this) and
                # the concat re-materializes the full K/V in HBM per step.
                # Kept as a shim for old callers; detach() here only drops
                # autograd linkage — the arrays are shared, not copied.
                warn_once(
                    "gpt-kv-cache-concat",
                    "tuple KV cache on GPTDecoderLayer is deprecated: it "
                    "grows by concat and recompiles the decode step at "
                    "every position. Use paddle_tpu.serving.KVCache / "
                    "GenerationEngine for O(1) static-shape decode.")
                k = ops.concat([cache[0], k], axis=1)
                v = ops.concat([cache[1], v], axis=1)
                cache = (k.detach(), v.detach())
            else:
                # serving.KVCache view (DecodeView/PrefillView): writes the
                # new rows in place (dynamic_update_slice at a traced
                # position index) and returns shape-stable K/V — the O(1)
                # decode path; causality/validity live in attn_mask
                k, v, cache = cache.update(k, v)
        if self._use_sep and cache is None and attn_mask is None:
            from ..distributed.meta_parallel import ring_attention

            attn = ring_attention(
                q, k, v, is_causal=True,
                dropout_p=self.attn_dropout if self.training else 0.0)
        else:
            attn = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.attn_dropout if self.training else 0.0,
                is_causal=cache is None,
            )
        attn = attn.reshape([b, s, local_width])
        x = residual + self.resid_dropout(self.out_proj(attn))

        residual = x
        y = self.ln_2(x)
        y = self.down_proj(F.gelu(self.up_proj(y), approximate=True))
        out = residual + self.resid_dropout(y)
        return out if cache is None else (out, cache)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = LayerList([GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                cache=None):
        h = self.embeddings(input_ids, position_ids)
        if cache is not None:
            # serving decode/prefill: one cache view per layer, collected
            # back for the engine (single-chip path; sep/mp stay training)
            new_cache = []
            for layer, c in zip(self.layers, cache):
                h, c = layer(h, attn_mask=attn_mask, cache=c)
                new_cache.append(c)
            return self.ln_f(h), new_cache
        # gate on the layers' frozen decision (made at construction against
        # the then-active hybrid mesh) so annotation and attention path agree
        if len(self.layers) and self.layers[0]._use_sep:
            from ..distributed.meta_parallel import split_sequence

            # keep activations sequence-sharded over sep between blocks
            h = split_sequence(h)
        for layer in self.layers:
            h = layer(h, attn_mask=attn_mask)
        return self.ln_f(h)


class GPTForCausalLM(Layer):
    """LM head tied to the input embedding (reference tied-weight pattern,
    SharedLayerDesc in PP)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                cache=None):
        w = self.gpt.embeddings.word_embeddings.weight  # [vocab, hidden]
        if cache is not None:
            h, new_cache = self.gpt(input_ids, position_ids, attn_mask,
                                    cache=cache)
            return ops.matmul(h, w, transpose_y=True), new_cache
        h = self.gpt(input_ids, position_ids, attn_mask)
        return ops.matmul(h, w, transpose_y=True)

    def generate(self, prompt_ids, max_new_tokens=32, eos_id=None,
                 max_len=None, prefill_buckets=None):
        """Greedy generation through the O(1) static-shape KV cache
        (:class:`paddle_tpu.serving.GenerationEngine`, batch 1). The
        engine — and its compiled prefill/decode executables — is cached
        on the model, so repeated calls never recompile. For concurrent
        request serving use ``serving.Scheduler`` directly."""
        from ..serving import GenerationEngine

        key = (max_len, tuple(prefill_buckets) if prefill_buckets else None)
        eng = getattr(self, "_serve_engine", None)
        if eng is None or getattr(self, "_serve_engine_key", None) != key:
            eng = GenerationEngine(self, max_batch=1, max_len=max_len,
                                   prefill_buckets=prefill_buckets)
            self._serve_engine = eng
            self._serve_engine_key = key
        return eng.generate(prompt_ids, max_new_tokens=max_new_tokens,
                            eos_id=eos_id)

    def loss(self, input_ids, labels):
        """Fused LM loss: head matmul + softmax-CE without materializing the
        ``[tokens, vocab]`` logits (``ops.fused.fused_linear_cross_entropy``)."""
        h = self.gpt(input_ids)
        w = self.gpt.embeddings.word_embeddings.weight
        return F.fused_linear_cross_entropy(h, w, labels)


# ---------------------------------------------------------------------------
# pipeline form
# ---------------------------------------------------------------------------

def build_gpt_pipeline_descs(cfg: GPTConfig):
    """Express GPTForCausalLM as PipelineLayer descs (reference
    ``hybrid_parallel_pp_transformer.py`` / pp_layers LayerDesc list), with
    the embedding shared between the first stage and the LM head."""
    from ..distributed.meta_parallel import LayerDesc, SharedLayerDesc

    def emb_forward(layer, x):
        return layer(x)

    def head_forward(layer, h):
        w = layer.word_embeddings.weight
        return ops.matmul(h, w, transpose_y=True)

    descs = [
        SharedLayerDesc("embed", GPTEmbeddings, forward_func=emb_forward, cfg=cfg),
    ]
    descs += [LayerDesc(GPTDecoderLayer, cfg) for _ in range(cfg.num_layers)]
    descs += [
        LayerDesc(LayerNorm, cfg.hidden_size, epsilon=cfg.layer_norm_eps),
        SharedLayerDesc("embed", GPTEmbeddings, forward_func=head_forward, cfg=cfg),
    ]
    return descs
