"""Flagship model families (reference: the fleet hybrid-parallel rank
scripts ``unittests/hybrid_parallel_mp_model.py`` / ``hybrid_parallel_pp_transformer.py``
and the ERNIE/GPT configs those tests model)."""
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForCausalLM,
    GPTDecoderLayer,
    GPTEmbeddings,
    build_gpt_pipeline_descs,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertModel,
    BertForPretraining,
    BertForSequenceClassification,
    bert_base,
    bert_large,
)
from .ernie import (  # noqa: F401
    ErnieConfig,
    ErnieModel,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieForTokenClassification,
    ErnieForQuestionAnswering,
    ErnieDataCollator,
    ernie_base,
    ernie_large,
)

__all__ = [
    "GPTConfig",
    "GPTModel",
    "GPTForCausalLM",
    "GPTDecoderLayer",
    "GPTEmbeddings",
    "build_gpt_pipeline_descs",
    "BertConfig",
    "BertModel",
    "BertForPretraining",
    "BertForSequenceClassification",
    "bert_base",
    "bert_large",
    "ErnieConfig",
    "ErnieModel",
    "ErnieForPretraining",
    "ErnieForSequenceClassification",
    "ErnieForTokenClassification",
    "ErnieForQuestionAnswering",
    "ErnieDataCollator",
    "ernie_base",
    "ernie_large",
]
