"""ERNIE-family encoder models (PaddlePaddle's flagship NLP family).

Reference scale target: the ERNIE configs the reference's hybrid-parallel
stack trains (SURVEY §7 M5 "ERNIE/GPT-style pretrain"; the fleet tests
model exactly this encoder shape). Architecturally ERNIE is a BERT-class
encoder with two additions kept here:

- a task-type embedding added into the input sum (ERNIE 2.0 continual
  multi-task pretraining; ``use_task_id``),
- sentence-order/NSP + MLM pretraining heads where the MLM projection is
  tied to the word embedding and runs through the fused linear+CE op so the
  ``[tokens, vocab]`` logits never materialize (ops/fused.py).

The knowledge-masking (word/phrase/entity) pretraining strategy is a data
pipeline concern; ``ErnieDataCollator`` implements span masking over
host-side numpy batches for the DataLoader path.

TPU notes: same mesh story as BERT/GPT — dp/sharding out of the box,
Column/RowParallel layers for mp via the shared transformer stack.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..framework.tensor import Tensor
from .. import ops
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer, ParamAttr
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..ops.fused import fused_linear_cross_entropy

__all__ = [
    "ErnieConfig", "ErnieModel", "ErnieForPretraining",
    "ErnieForSequenceClassification", "ErnieForTokenClassification",
    "ErnieForQuestionAnswering", "ErnieDataCollator",
    "ernie_base", "ernie_large",
]


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 513
    type_vocab_size: int = 2
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    hidden_act: str = "gelu"


def ernie_base():
    return ErnieConfig()


def ernie_large():
    return ErnieConfig(hidden_size=1024, num_layers=24, num_heads=16,
                       intermediate_size=4096)


class ErnieEmbeddings(Layer):
    """word + position + sentence(token-type) [+ task] -> LN -> dropout."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        init = ParamAttr(initializer=Normal(std=cfg.initializer_range))
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size,
                                               weight_attr=init)
        self.use_task_id = cfg.use_task_id
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(cfg.task_type_vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=init)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout, mode="upscale_in_train")

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = Tensor(
                np.arange(s, dtype=np.int64)[None, :].repeat(b, 0))
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = ops.zeros_like(input_ids)
            h = h + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(h))


class ErniePooler(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, h):
        return self.dense(h[:, 0]).tanh()


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_dropout,
            act_dropout=0.0, normalize_before=False,
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = ErniePooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        mask = None
        if attention_mask is not None:
            if len(attention_mask.shape) == 2:
                neg = (1.0 - attention_mask.astype("float32")) * -1e4
                mask = neg.unsqueeze(1).unsqueeze(2)
            else:
                mask = attention_mask
        out = self.encoder(h, src_mask=mask)
        return out, self.pooler(out)


class ErnieLMPredictionHead(Layer):
    """transform -> LN -> tied-embedding projection (+bias). The projection
    itself lives inside the fused linear+CE op at loss time."""

    def __init__(self, cfg: ErnieConfig, embedding_weights):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.activation = getattr(F, cfg.hidden_act)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.decoder_weight = embedding_weights  # tied [vocab, hidden]
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)

    def hidden(self, h):
        return self.layer_norm(self.activation(self.transform(h)))

    def forward(self, h):
        h = self.hidden(h)
        return ops.matmul(h, self.decoder_weight,
                          transpose_y=True) + self.decoder_bias


class ErnieForPretraining(Layer):
    """MLM + sentence-order heads (reference ErnieForPretraining)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.lm_head = ErnieLMPredictionHead(
            cfg, self.ernie.embeddings.word_embeddings.weight)
        self.nsp_head = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        seq, pooled = self.ernie(input_ids, token_type_ids,
                                 attention_mask=attention_mask,
                                 task_type_ids=task_type_ids)
        return self.lm_head(seq), self.nsp_head(pooled)

    def loss(self, input_ids, mlm_labels, nsp_labels=None,
             token_type_ids=None, attention_mask=None, task_type_ids=None,
             ignore_index=-100):
        """MLM (+ optional sentence-order) loss; mlm_labels uses -100 for
        unmasked positions. The biased vocab projection goes through the
        fused linear+CE kernel — logits never materialize."""
        seq, pooled = self.ernie(input_ids, token_type_ids,
                                 attention_mask=attention_mask,
                                 task_type_ids=task_type_ids)
        h = self.lm_head.hidden(seq)
        mlm = fused_linear_cross_entropy(
            h, self.lm_head.decoder_weight, mlm_labels,
            bias=self.lm_head.decoder_bias, ignore_index=ignore_index)
        if nsp_labels is None:
            return mlm
        nsp = F.cross_entropy(self.nsp_head(pooled),
                              nsp_labels.reshape([-1, 1])).mean()
        return mlm + nsp


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout if dropout is None else dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask,
                               task_type_ids=task_type_ids)
        return self.classifier(self.dropout(pooled))


class ErnieForTokenClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout if dropout is None else dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids,
                            attention_mask=attention_mask,
                            task_type_ids=task_type_ids)
        return self.classifier(self.dropout(seq))


class ErnieForQuestionAnswering(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.classifier = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                task_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids,
                            attention_mask=attention_mask,
                            task_type_ids=task_type_ids)
        logits = self.classifier(seq)
        return logits[:, :, 0], logits[:, :, 1]  # start, end


class ErnieDataCollator:
    """Knowledge-masking collator (host-side numpy): masks contiguous spans
    (ERNIE's phrase/entity-level masking) instead of independent tokens.
    Produces (input_ids, mlm_labels) with -100 on unmasked positions."""

    def __init__(self, vocab_size, mask_token_id=3, mlm_prob=0.15,
                 max_span=3, seed=0):
        self.vocab_size = vocab_size
        self.mask_token_id = mask_token_id
        self.mlm_prob = mlm_prob
        self.max_span = max_span
        self.rng = np.random.RandomState(seed)

    def __call__(self, batch_ids):
        ids = np.array(batch_ids, dtype=np.int64, copy=True)
        labels = np.full_like(ids, -100)
        b, s = ids.shape
        n_mask = min(max(1, int(s * self.mlm_prob)), s)
        for i in range(b):
            masked = 0
            while masked < n_mask:
                span = int(self.rng.randint(1, self.max_span + 1))
                span = min(span, s)
                # inclusive of start = s - span so the final token is maskable
                start = int(self.rng.randint(0, s - span + 1))
                for j in range(start, min(start + span, s)):
                    if labels[i, j] != -100:
                        continue
                    labels[i, j] = ids[i, j]
                    r = self.rng.rand()
                    if r < 0.8:
                        ids[i, j] = self.mask_token_id
                    elif r < 0.9:
                        ids[i, j] = self.rng.randint(0, self.vocab_size)
                    masked += 1
                if masked >= n_mask:
                    break
        return ids, labels
