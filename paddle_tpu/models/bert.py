"""BERT-family encoder models.

Reference scale target: the BERT configs the reference's fleet/AMP stack
trains (``python/paddle/fluid/tests/unittests/test_bert*`` and the
BERT-large tokens/sec/chip metric in BASELINE.md). Encoder built from the
framework's TransformerEncoder; the MLM head reuses the fused
linear+cross-entropy op so the ``[tokens, vocab]`` logits never materialize
(ops/fused.py), same as the GPT flagship.

TPU notes: under the fleet hybrid mesh the encoder works with dp/sharding
out of the box (batch sharding + ZeRO placement); mp for BERT reuses the
Column/RowParallelLinear layers if wired into a custom encoder layer.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..framework.tensor import Tensor
from .. import ops
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer, ParamAttr
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["BertConfig", "BertEmbeddings", "BertModel", "BertPooler",
           "BertForPretraining", "BertForSequenceClassification"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12


def bert_base():
    return BertConfig()


def bert_large():
    return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096)


class BertEmbeddings(Layer):
    """word + position + token-type embeddings -> LN -> dropout."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = ParamAttr(initializer=Normal(std=cfg.initializer_range))
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout, mode="upscale_in_train")

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            # (1, s): the embedding broadcasts over batch — materializing
            # the batch dim would force a constant where dynamic-batch
            # export (symbolic b) must stay polymorphic
            import jax.numpy as jnp

            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, h):
        return self.dense(h[:, 0]).tanh()


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout, activation="gelu",
            attn_dropout=cfg.attention_dropout,
            act_dropout=0.0, normalize_before=False,
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        """attention_mask: [b, s] 1/0 padding mask (paddle convention) or a
        broadcastable additive mask."""
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            if len(attention_mask.shape) == 2:
                # [b, s] keep-mask -> additive [b, 1, 1, s]
                neg = (1.0 - attention_mask.astype("float32")) * -1e4
                mask = neg.unsqueeze(1).unsqueeze(2)
            else:
                mask = attention_mask
        out = self.encoder(h, src_mask=mask)
        return out, self.pooler(out)


class BertForPretraining(Layer):
    """MLM + NSP heads (reference BertForPretraining); the MLM loss uses the
    fused linear+CE path with the tied word-embedding matrix."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_ln = LayerNorm(cfg.hidden_size,
                                      epsilon=cfg.layer_norm_eps)
        self.nsp_head = Linear(cfg.hidden_size, 2)

    def _mlm_hidden(self, input_ids, token_type_ids, attention_mask):
        """Shared MLM head pipeline: encoder -> transform -> gelu -> LN."""
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        h = self.transform_ln(F.gelu(self.transform(seq), approximate=True))
        return h, pooled

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h, pooled = self._mlm_hidden(input_ids, token_type_ids, attention_mask)
        w = self.bert.embeddings.word_embeddings.weight
        logits = ops.matmul(h, w, transpose_y=True)
        return logits, self.nsp_head(pooled)

    def loss(self, input_ids, mlm_labels, token_type_ids=None,
             attention_mask=None, nsp_labels=None, ignore_index=-100):
        """Fused MLM loss (+ optional NSP)."""
        h, pooled = self._mlm_hidden(input_ids, token_type_ids, attention_mask)
        w = self.bert.embeddings.word_embeddings.weight
        loss = F.fused_linear_cross_entropy(h, w, mlm_labels,
                                            ignore_index=ignore_index)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(
                self.nsp_head(pooled), nsp_labels.reshape([-1, 1])).mean()
        return loss


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes=2, dropout=None):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout if dropout is None else dropout,
                               mode="upscale_in_train")
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))

    def scorer(self, max_batch=8, seq_buckets=None, max_seq=None):
        """Serving path: a bucketed compile-once-per-bucket batch scorer
        (:class:`paddle_tpu.serving.EncoderScorer`) — requests are padded
        to ``[max_batch, bucket]`` so one executable per sequence bucket
        serves every request mix; padding rows are masked and dropped."""
        from ..serving import EncoderScorer

        return EncoderScorer(self, max_batch=max_batch,
                             seq_buckets=seq_buckets, max_seq=max_seq)
