"""paddle.signal — frame / overlap_add / stft / istft.

Reference: ``python/paddle/signal.py`` (kernels ``phi/kernels/*/frame_*``,
``overlap_add_*``, stft built from frame+matmul). TPU-native: framing is a
gather-free strided reshape window (XLA lowers to slices), the DFT is the
FFT HLO via :mod:`paddle_tpu.fft`, and overlap-add is a scatter-add the
compiler fuses; everything traces/jits/differentiates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fft as pfft
from .framework.tensor import Tensor
from .ops.dispatch import apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames of size ``frame_length`` every ``hop_length``
    samples along ``axis`` (reference ``signal.py:32``). axis=-1 yields
    ``[..., frame_length, num_frames]``; axis=0 yields
    ``[num_frames, frame_length, ...]``."""
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    size = x.shape[axis]
    if frame_length > size:
        raise ValueError(
            f"frame_length ({frame_length}) > axis size ({size})")
    n_frames = 1 + (size - frame_length) // hop_length

    def fwd(a):
        ax = axis % a.ndim
        idx = (np.arange(frame_length)[:, None]
               + hop_length * np.arange(n_frames)[None, :])  # [fl, nf]
        out = jnp.take(a, jnp.asarray(idx.reshape(-1)), axis=ax)
        shape = list(a.shape)
        shape[ax:ax + 1] = [frame_length, n_frames]
        out = out.reshape(shape)
        if axis == 0:
            # reference axis=0 convention: [num_frames, frame_length, ...]
            out = jnp.swapaxes(out, 0, 1)
        return out

    return apply_op("frame", fwd, (x,), {})


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of :func:`frame`: add overlapping frames back
    (reference ``signal.py:153``). axis=-1 input ``[..., frame_length,
    num_frames]``; axis=0 input ``[num_frames, frame_length, ...]``."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")

    def fwd(a):
        if axis == 0:
            a = jnp.swapaxes(a, 0, 1)  # -> [fl, nf, ...], frames at dim 1
            fl, nf = a.shape[0], a.shape[1]
            out_len = (nf - 1) * hop_length + fl
            tail = a.shape[2:]
            acc = jnp.zeros((out_len,) + tail, a.dtype)
            idx = (np.arange(fl)[:, None]
                   + hop_length * np.arange(nf)[None, :]).reshape(-1)
            acc = acc.at[jnp.asarray(idx)].add(
                a.reshape((fl * nf,) + tail))
            return acc
        fl, nf = a.shape[-2], a.shape[-1]
        out_len = (nf - 1) * hop_length + fl
        lead = a.shape[:-2]
        acc = jnp.zeros(lead + (out_len,), a.dtype)
        idx = (np.arange(fl)[:, None]
               + hop_length * np.arange(nf)[None, :]).reshape(-1)
        flat = a.reshape(lead + (fl * nf,))
        acc = acc.at[..., jnp.asarray(idx)].add(flat)
        return acc

    return apply_op("overlap_add", fwd, (x,), {})


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference ``signal.py:237``).
    x: ``[..., seq_len]`` real or complex; returns
    ``[..., n_fft//2+1 | n_fft, num_frames]`` complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if win_length > n_fft:
        raise ValueError(f"win_length ({win_length}) > n_fft ({n_fft})")
    if window is not None:
        w = window._value if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[0] != win_length:
            raise ValueError(
                f"window length {w.shape[0]} != win_length {win_length}")
    else:
        w = jnp.ones((win_length,), jnp.float32)
    if win_length != n_fft:
        left = (n_fft - win_length) // 2
        w = jnp.pad(w, (left, n_fft - win_length - left))

    is_complex = "complex" in str(x.dtype)
    if is_complex and onesided:
        raise ValueError("onesided is not supported for complex input")

    def fwd(a, wv):
        if center:
            pad_width = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad_width, mode=pad_mode)
        size = a.shape[-1]
        n_frames = 1 + (size - n_fft) // hop_length
        idx = (np.arange(n_fft)[:, None]
               + hop_length * np.arange(n_frames)[None, :]).reshape(-1)
        frames = jnp.take(a, jnp.asarray(idx), axis=-1)
        frames = frames.reshape(a.shape[:-1] + (n_fft, n_frames))
        frames = frames * wv[:, None]
        spec = (jnp.fft.rfft(frames, axis=-2) if (onesided and not is_complex)
                else jnp.fft.fft(frames, axis=-2))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    return apply_op("stft", fwd, (x, Tensor(w)), {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT, least-squares (NOLA-weighted) overlap-add
    (reference ``signal.py:395``). x: ``[..., n_bins, num_frames]``."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if win_length > n_fft:
        raise ValueError(f"win_length ({win_length}) > n_fft ({n_fft})")
    if window is not None:
        w = window._value if isinstance(window, Tensor) else jnp.asarray(window)
    else:
        w = jnp.ones((win_length,), jnp.float32)
    if win_length != n_fft:
        left = (n_fft - win_length) // 2
        w = jnp.pad(w, (left, n_fft - win_length - left))

    # NOLA check, eager only (the reference raises on violation; clamping
    # inside the trace would silently distort) — skipped when the window is
    # a tracer, where istft must stay traceable and the clamp still guards
    if not isinstance(w, jax.core.Tracer):
        nf = int(x.shape[-1])
        fl = int(w.shape[0])
        out_len = (nf - 1) * hop_length + fl
        wsq_np = np.asarray(w, dtype=np.float64) ** 2
        env_np = np.zeros(out_len)
        for f in range(nf):
            env_np[f * hop_length: f * hop_length + fl] += wsq_np
        if center:
            region = env_np[n_fft // 2: out_len - n_fft // 2]
        else:
            # without centering the first/last (fl - hop) samples taper by
            # construction (partial overlap) — that is not a NOLA violation;
            # check the steady-state interior only
            edge = max(fl - hop_length, 0)
            region = env_np[edge: out_len - edge]
        if length is not None:
            region = region[:length]
        if region.size and region.min() < 1e-11:
            raise ValueError(
                "istft: window fails the NOLA (nonzero overlap-add) "
                f"constraint for hop_length={hop_length} "
                f"(envelope min {region.min():.3g})"
            )

    def fwd(a, wv):
        if onesided:
            frames = jnp.fft.irfft(a, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(a, axis=-2)
            if not return_complex:
                frames = frames.real
        if normalized:
            frames = frames * jnp.sqrt(jnp.asarray(n_fft, frames.dtype
                                                   if frames.dtype != jnp.complex64
                                                   else jnp.float32))
        frames = frames * wv[:, None]
        fl, nf = frames.shape[-2], frames.shape[-1]
        out_len = (nf - 1) * hop_length + fl
        lead = frames.shape[:-2]
        idx = jnp.asarray((np.arange(fl)[:, None]
                           + hop_length * np.arange(nf)[None, :]).reshape(-1))
        acc = jnp.zeros(lead + (out_len,), frames.dtype)
        acc = acc.at[..., idx].add(frames.reshape(lead + (fl * nf,)))
        # NOLA normalization: divide by the summed squared window envelope
        wsq = (wv ** 2)[:, None] * jnp.ones((1, nf), wv.dtype)
        env = jnp.zeros((out_len,), wv.dtype)
        env = env.at[idx].add(wsq.reshape(-1))
        out = acc / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op("istft", fwd, (x, Tensor(w)), {})
