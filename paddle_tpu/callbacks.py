"""paddle.callbacks — re-export of the hapi callback family
(reference ``python/paddle/callbacks.py``)."""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    TelemetryLogger,
    VisualDL,
)
