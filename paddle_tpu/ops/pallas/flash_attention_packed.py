"""Seq-major ("packed") flash attention: kernels that read the model's
native ``(batch, seq, heads*head_dim)`` activation layout directly.

Motivation (measured on v5e, GPT-2 124M b16 s1024): the layout-swapping
``flash_attention`` kernel forces ``(b,s,h,d) <-> (b,h,s,d)`` transposes
around every attention call — fwd q/k/v + out, and their autodiff duals —
which profiled at ~14% of device step time (24 standalone transpose ops,
~25 ms/step).  These kernels eliminate every one of those transposes: the
qkv-projection output feeds the kernel as-is and the kernel output feeds
the out-projection as-is.

Design: the grid is ``(batch, head_group, q_block, k_block)`` where a head
group is the set of heads whose packed lane range spans exactly 128 lanes
(2 heads at d=64, 1 at d=128, 4 at d=32 …).  Each q/k/v/o block is a
``(1, block, 128)`` slice of the packed array selected purely by the
BlockSpec index map — 128-lane alignment keeps Mosaic happy where per-head
``(1, block, 1, d)`` blocks and dynamic head indexing are rejected (tried;
see repo build notes) — and the kernel unrolls a static loop over the
heads inside the group, slicing each head's ``d``-wide lane range with
static offsets (Mosaic accepts static 64-aligned lane slices).  A VMEM-
budget bonus vs a full-embedding block: per-head softmax-stat tiles pad
their 8-lane minor dim to 128 lanes, so carrying all ``h`` heads in one
kernel instance costs ``h``× that padding; a head group carries at most
128/d of it (the full-E variant OOM'd scoped VMEM at 18 MB > 16 MB).

Same math as ``flash_attention.py`` (online softmax fwd; FlashAttention-2
split dq / dk+dv backward recomputing p from the saved logsumexp; in-kernel
hardware-PRNG dropout with the per-tile reseed scheme).  Supports causal
masking, an optional SHARED 2-D additive bias ``(sq, sk)`` (streamed
per-tile; per-batch/per-head 4-D biases route to the layout-swapping
kernel), and dropout.

Reference capability: fused attention fwd+bwd in
``paddle/fluid/operators/fused/fused_attention_op.cu`` / ``fmha_ref.h``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import (
    LANES,
    NEG_INF,
    STAT_LANES,
    _causal_mask,
    _causal_run,
    _dropout_mask,
    _inject_none,
    _keep_bits,
    _pick_block,
    _zero_masked_rows,
)

DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
DEFAULT_BWD_BLOCK = 512


def _group_width(d):
    """(heads_per_group, lane width of one group block)."""
    if d >= LANES:
        return (1, d) if d % LANES == 0 else (0, 0)
    return (LANES // d, LANES) if LANES % d == 0 else (0, 0)


def _tile_bias(b_ref, qi, ki, block_q, block_k, offset, causal):
    """Per-tile additive term, computed ONCE per kernel instance and shared
    by every head in the group (the causal iota pair costs real VPU time —
    paying it per head doubled the masking work at d=64)."""
    add = None if b_ref is None else b_ref[...].astype(jnp.float32)
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        neg = jnp.where(cols <= rows + offset, 0.0, NEG_INF)
        add = neg if add is None else add + neg
    return add


def _head_logits(q_ref, k_ref, add, j, d, scale):
    qh = q_ref[0, :, j * d:(j + 1) * d]
    kh = k_ref[0, :, j * d:(j + 1) * d]
    s = jax.lax.dot_general(
        qh, kh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    if add is not None:
        s = s + add
    return s


DROP_UNIT = 512  # canonical dropout tile: mask depends only on ABSOLUTE
                 # (row-unit, col-unit), so fwd and bwd may tile differently


def _drop(seed_ref, j, hpg, qi, ki, shape, dropout_p):
    """Keep-mask for this tile, assembled from canonical 512x512 units so
    the forward (1024-tiles, single-k fast path) and backward (512-tiles,
    VMEM headroom) regenerate identical bits; non-512-multiple blocks fall
    back to the tile-shape-keyed draw (the caller then unifies fwd/bwd
    block sizes)."""
    head = pl.program_id(1) * hpg + j
    bq, bk = shape
    if bq % DROP_UNIT or bk % DROP_UNIT:
        return _dropout_mask(seed_ref, qi, ki, shape, dropout_p, head=head)
    bb = pl.program_id(0)
    ru, cu = bq // DROP_UNIT, bk // DROP_UNIT
    rows = []
    for ur in range(ru):
        cols = []
        for uc in range(cu):
            aur = qi * ru + ur
            auc = ki * cu + uc
            pltpu.prng_seed(seed_ref[0] ^ (aur * 65536 + auc),
                            seed_ref[1] ^ (bb * 1024 + head))
            cols.append(_keep_bits((DROP_UNIT, DROP_UNIT), dropout_p))
        rows.append(cols[0] if cu == 1 else jnp.concatenate(cols, axis=1))
    return rows[0] if ru == 1 else jnp.concatenate(rows, axis=0)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, hpg, d, scale, causal, block_q,
                block_k, offset, dropout_p, single):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    if single:
        # nk == 1 (whole key range in one tile): plain softmax — no online
        # rescale, no m/l scratch round-trips, no acc rescale multiply
        add = _tile_bias(b_ref, qi, ki, block_q, block_k, offset, causal)
        for j in range(hpg):
            s = _head_logits(q_ref, k_ref, add, j, d, scale)
            m = jnp.max(s, axis=-1, keepdims=True)
            # fully-masked q rows (causal sq > sk): output 0, lse NEG_INF
            p = _zero_masked_rows(jnp.exp(s - m), m)
            l = jnp.sum(p, axis=-1, keepdims=True)
            l_safe = jnp.where(l == 0.0, 1.0, l)
            if dropout_p > 0.0:
                keep = _drop(seed_ref, j, hpg, qi, ki, s.shape, dropout_p)
                p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
            vh = v_ref[0, :, j * d:(j + 1) * d]
            o_ref[0, :, j * d:(j + 1) * d] = (jax.lax.dot_general(
                p.astype(vh.dtype), vh,
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            ) / l_safe).astype(o_ref.dtype)
            if lse_ref is not None:
                lse_ref[0, j] = jnp.broadcast_to(
                    m + jnp.log(l_safe), lse_ref.shape[2:])
        return

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = _causal_run(qi, ki, block_q, block_k, offset) if causal else (ki >= 0)

    def _body(masked):
        add = _tile_bias(b_ref, qi, ki, block_q, block_k, offset, masked)
        # phase-separated over the head group: ALL QK matmuls first, then
        # the VPU softmax phase, then ALL PV matmuls — adjacent independent
        # MXU and VPU work lets Mosaic overlap units instead of serializing
        # QK -> softmax -> PV per head (the per-head chain idles the MXU
        # through every softmax)
        for j in range(hpg):
            s = _head_logits(q_ref, k_ref, add, j, d, scale)
            m_prev = m_ref[j][:, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            if masked or b_ref is not None:
                # rows fully masked SO FAR keep l = 0 so _finish emits
                # output 0 / lse NEG_INF (same contract as the single
                # path). A shared bias can fully mask rows in ANY tile
                # (padding masks), so the guard stays whenever a bias is
                # streamed; pure-causal interior tiles skip it (their rows
                # always have visible keys)
                p = _zero_masked_rows(p, m_new)
            l_new = l_ref[j][:, 0:1] * alpha + jnp.sum(p, axis=-1,
                                                       keepdims=True)
            if dropout_p > 0.0:
                keep = _drop(seed_ref, j, hpg, qi, ki, s.shape, dropout_p)
                p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
            vh = v_ref[0, :, j * d:(j + 1) * d]
            acc_ref[0, :, j * d:(j + 1) * d] = (
                acc_ref[0, :, j * d:(j + 1) * d] * alpha
                + jax.lax.dot_general(
                    p.astype(vh.dtype), vh,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
            m_ref[j] = jnp.broadcast_to(m_new, m_ref.shape[1:])
            l_ref[j] = jnp.broadcast_to(l_new, l_ref.shape[1:])

    if causal:
        # interior/boundary split: tiles fully below the diagonal skip the
        # per-element iota/compare/select masking — the online softmax at
        # long s is VPU-bound, and interior tiles dominate (profiled 2x
        # forward-kernel speedup at s=8192)
        full = ki * block_k + block_k - 1 <= qi * block_q + offset

        @pl.when(run & full)
        def _interior():
            _body(False)

        @pl.when(run & jnp.logical_not(full))
        def _boundary():
            _body(True)
    else:
        @pl.when(run)
        def _all():
            _body(False)

    @pl.when(ki == nk - 1)
    def _finish():
        for j in range(hpg):
            l = l_ref[j][:, 0:1]
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, :, j * d:(j + 1) * d] = (
                acc_ref[0, :, j * d:(j + 1) * d] / l_safe
            ).astype(o_ref.dtype)
            if lse_ref is not None:
                lse_ref[0, j] = jnp.broadcast_to(
                    m_ref[j][:, 0:1] + jnp.log(l_safe), lse_ref.shape[2:]
                )


def _bwd_fused_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, o_ref,
                      lse_ref, dq_ref, dk_ref, dv_ref,
                      dq_sc, dk_acc, dv_acc, *, hpg, d, scale, causal,
                      block_q, block_k, offset, dropout_p):
    """Single-sweep backward: grid (b, group, K block, Q block). dk/dv
    accumulate in per-k-block scratch over the inner q sweep (written once
    per k block); dq accumulates in a scratch slab holding EVERY q block
    (``(nq, block_q, width)`` f32 — VMEM persists across the whole grid),
    written through to the revisited dq output each step so the LAST
    write (ki == nk-1) carries the full sum in both compiled and
    interpret modes. The payoff over split dq / dkv kernels: s,
    p=exp(s-lse), dp and ds are computed ONCE instead of twice — measured
    on v5e they dominate the backward. The slab caps supported seq_q
    (~16k at 512 blocks); longer sequences route to the layout-swapping
    kernel."""
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(ki == 0)
    def _dq_init():
        dq_sc[qi] = jnp.zeros_like(dq_sc[qi])

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _causal_run(qi, ki, block_q, block_k, offset) if causal else (qi >= 0)

    def _body(masked):
        add = _tile_bias(b_ref, qi, ki, block_q, block_k, offset, masked)
        for j in range(hpg):
            s = _head_logits(q_ref, k_ref, add, j, d, scale)
            lse_j = lse_ref[0, j][:, 0:1]
            p = jnp.exp(s - lse_j)
            if masked or b_ref is not None:
                # fully-masked rows saved lse == NEG_INF: zero gradients
                # (bias-masked rows can appear in any tile — see fwd)
                p = _zero_masked_rows(p, lse_j)
            doh = do_ref[0, :, j * d:(j + 1) * d]
            oh = o_ref[0, :, j * d:(j + 1) * d]
            delta = jnp.sum(
                doh.astype(jnp.float32) * oh.astype(jnp.float32),
                axis=-1, keepdims=True,
            )
            vh = v_ref[0, :, j * d:(j + 1) * d]
            dp = jax.lax.dot_general(
                doh, vh,
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
            if dropout_p > 0.0:
                keep = _drop(seed_ref, j, hpg, qi, ki, s.shape, dropout_p)
                inv = 1.0 / (1.0 - dropout_p)
                p_d = jnp.where(keep, p * inv, 0.0)
                dp = jnp.where(keep, dp * inv, 0.0)
            else:
                p_d = p
            dv_acc[0, :, j * d:(j + 1) * d] = (
                dv_acc[0, :, j * d:(j + 1) * d] + jax.lax.dot_general(
                    p_d.astype(doh.dtype), doh,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
            ds = p * (dp - delta) * scale
            qh = q_ref[0, :, j * d:(j + 1) * d]
            dk_acc[0, :, j * d:(j + 1) * d] = (
                dk_acc[0, :, j * d:(j + 1) * d] + jax.lax.dot_general(
                    ds.astype(qh.dtype), qh,
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
            kh = k_ref[0, :, j * d:(j + 1) * d]
            dq_sc[qi, :, j * d:(j + 1) * d] = (
                dq_sc[qi, :, j * d:(j + 1) * d] + jax.lax.dot_general(
                    ds.astype(kh.dtype), kh,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )

    if causal:
        # interior/boundary split (see _fwd_kernel): only tiles crossing
        # the diagonal pay the per-element masking and lse row-guard
        full = ki * block_k + block_k - 1 <= qi * block_q + offset

        @pl.when(run & full)
        def _interior():
            _body(False)

        @pl.when(run & jnp.logical_not(full))
        def _boundary():
            _body(True)
    else:
        @pl.when(run)
        def _all():
            _body(False)

    # write-through every step: intermediate write-backs are overwritten by
    # the revisit at the next ki; the ki == nk-1 write is the full sum
    dq_ref[0] = dq_sc[qi].astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[0].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[0].astype(dv_ref.dtype)


def _seed_spec(seed):
    return None if seed is None else pl.BlockSpec(memory_space=pltpu.SMEM)


def _bias_spec(bias, block_q, block_k, kv_major=False):
    """Shared 2-D (sq, sk) bias, streamed per (q_block, k_block) tile."""
    if bias is None:
        return None
    if kv_major:
        return pl.BlockSpec((block_q, block_k),
                            lambda bb, hg, ki, qi: (qi, ki))
    return pl.BlockSpec((block_q, block_k), lambda bb, hg, qi, ki: (qi, ki))


def _check(q, k, v, h):
    b, sq, e = q.shape
    bk, sk, ek = k.shape
    assert v.shape == k.shape, (v.shape, k.shape)
    assert (bk, ek) == (b, e), (q.shape, k.shape)
    assert e % h == 0, (e, h)
    return b, sq, sk, e // h


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, bias, seed, h, scale, causal, block_q, block_k,
           interpret, dropout_p, bwd_block):
    return _fwd_impl(q, k, v, bias, seed, h, scale, causal, block_q, block_k,
                     interpret, dropout_p, need_stats=False)


def _fwd_impl(q, k, v, bias, seed, h, scale, causal, block_q, block_k,
              interpret, dropout_p, need_stats=True):
    b, sq, sk, d = _check(q, k, v, h)
    hpg, width = _group_width(d)
    ng = h // hpg
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq

    def qmap(bb, hg, qi, ki):
        return (bb, qi, hg)

    def kmap(bb, hg, qi, ki):
        return (bb, ki, hg)

    in_specs = [
        _seed_spec(seed),
        pl.BlockSpec((1, block_q, width), qmap),
        pl.BlockSpec((1, block_k, width), kmap),
        pl.BlockSpec((1, block_k, width), kmap),
        _bias_spec(bias, block_q, block_k),
    ]
    kernel = functools.partial(
        _fwd_kernel, hpg=hpg, d=d, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, dropout_p=dropout_p,
        single=(nk == 1),
    )
    # full kernel signature: (seed, q, k, v, bias, o, lse, <scratch>)
    missing = ([0] if seed is None else []) + ([4] if bias is None else [])
    if need_stats:
        out_specs = [
            pl.BlockSpec((1, block_q, width), qmap),
            pl.BlockSpec((1, hpg, block_q, STAT_LANES),
                         lambda bb, hg, qi, ki: (bb, hg, qi, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, STAT_LANES), jnp.float32),
        ]
    else:
        missing.append(6)
        out_specs = pl.BlockSpec((1, block_q, width), qmap)
        out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if missing:
        kernel = _inject_none(kernel, *missing)
    return pl.pallas_call(
        kernel,
        grid=(b, ng, nq, nk),
        in_specs=[s for s in in_specs if s is not None],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((1, block_q, width), jnp.float32),
            pltpu.VMEM((hpg, block_q, STAT_LANES), jnp.float32),
            pltpu.VMEM((hpg, block_q, STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * h * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(2 * (q.size + k.size + v.size + q.size)),
            transcendentals=int(b * h * sq * sk),
        ),
    )(*[x for x in (seed, q, k, v, bias) if x is not None])


def _fwd(q, k, v, bias, seed, h, scale, causal, block_q, block_k, interpret,
         dropout_p, bwd_block):
    out, lse = _fwd_impl(q, k, v, bias, seed, h, scale, causal, block_q,
                         block_k, interpret, dropout_p, need_stats=True)
    return out, (q, k, v, bias, seed, out, lse)


def _bwd(h, scale, causal, block_q, block_k, interpret, dropout_p, bwd_block,
         res, g):
    q, k, v, bias, seed, out, lse = res
    b, sq, sk, d = _check(q, k, v, h)
    hpg, width = _group_width(d)
    ng = h // hpg
    # backward streams q/k/v + do/o + grads (~3x fwd working set): its own,
    # smaller block size keeps it inside the 16 MB scoped-VMEM budget while
    # the forward runs 1024-wide tiles
    block_q = _pick_block(sq, bwd_block)
    block_k = _pick_block(sk, bwd_block)
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq

    def qmap(bb, hg, ki, qi):
        return (bb, qi, hg)

    def kmap(bb, hg, ki, qi):
        return (bb, ki, hg)

    kernel = functools.partial(
        _bwd_fused_kernel, hpg=hpg, d=d, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, dropout_p=dropout_p,
    )
    # full signature: (seed, q, k, v, bias, do, o, lse, dq, dk, dv,
    #                  <dq slab, dk acc, dv acc scratch>)
    missing = ([0] if seed is None else []) + ([4] if bias is None else [])
    if missing:
        kernel = _inject_none(kernel, *missing)
    in_specs = [
        _seed_spec(seed),
        pl.BlockSpec((1, block_q, width), qmap),       # q
        pl.BlockSpec((1, block_k, width), kmap),       # k
        pl.BlockSpec((1, block_k, width), kmap),       # v
        _bias_spec(bias, block_q, block_k, kv_major=True),
        pl.BlockSpec((1, block_q, width), qmap),       # do
        pl.BlockSpec((1, block_q, width), qmap),       # o
        pl.BlockSpec((1, hpg, block_q, STAT_LANES),
                     lambda bb, hg, ki, qi: (bb, hg, qi, 0)),  # lse
    ]
    operands = [x for x in (seed, q, k, v, bias) if x is not None]
    operands += [g, out, lse]
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b, ng, nk, nq),
        in_specs=[sp for sp in in_specs if sp is not None],
        out_specs=[
            pl.BlockSpec((1, block_q, width), qmap),
            pl.BlockSpec((1, block_k, width), kmap),
            pl.BlockSpec((1, block_k, width), kmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((nq, block_q, width), jnp.float32),
            pltpu.VMEM((1, block_k, width), jnp.float32),
            pltpu.VMEM((1, block_k, width), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    if bias is None:
        dbias = None
    else:
        # shared constant 2-D masks only (router guarantees stop_gradient)
        dbias = jnp.zeros_like(bias)
    dseed = None if seed is None else np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_fwd, _bwd)


MAX_BWD_SLAB_BYTES = 10 * 2 ** 20  # dq scratch slab cap (VMEM budget)


def supports(seq_q, seq_k, num_heads, embed_dim,
             block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Shape gate: lane-tileable seqs; head_dim must pack into 128-lane
    groups (d a divisor or multiple of 128) with the head count divisible
    by the group size; seq_q bounded by the backward's resident dq slab
    (~16k at 128-lane groups) — longer routes to the layout-swapping
    kernel (or ring attention)."""
    if embed_dim % num_heads:
        return False
    d = embed_dim // num_heads
    hpg, width = _group_width(d)
    if not hpg or num_heads % hpg:
        return False
    if seq_q * width * 4 > MAX_BWD_SLAB_BYTES:
        return False
    return _pick_block(seq_q, block_q) > 0 and _pick_block(seq_k, block_k) > 0


def flash_attention_packed(q, k, v, num_heads, bias=None, *, causal=False,
                           scale=None, block_q=DEFAULT_BLOCK_Q,
                           block_k=DEFAULT_BLOCK_K,
                           bwd_block=DEFAULT_BWD_BLOCK, interpret=None,
                           dropout_p=0.0, dropout_seed=None):
    """Flash attention over packed ``(batch, seq, heads*head_dim)`` arrays.

    Zero layout changes: inputs and output stay seq-major, exactly as the
    qkv projection produces them and the out-projection consumes them.
    ``bias`` (optional) must be a SHARED 2-D ``(sq, sk)`` additive mask
    (constant — no bias gradient path); use :func:`flash_attention` for
    per-batch/per-head biases.
    """
    from ...framework.flags import flag_value
    from . import interpret_requested

    if interpret is None:
        interpret = interpret_requested()
    b, sq, e = q.shape
    sk = k.shape[1]
    h = int(num_heads)
    d = e // h
    dropout_p = float(dropout_p)
    if dropout_p > 0.0:
        if interpret:
            raise ValueError(
                "in-kernel attention dropout needs the TPU hardware PRNG; "
                "no interpret-mode lowering exists (use the einsum path)"
            )
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed")
        if h >= 1024:
            raise ValueError(
                f"in-kernel dropout supports < 1024 heads (got {h})"
            )
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape(2)
    else:
        seed = None
    if (block_q == DEFAULT_BLOCK_Q and block_k == DEFAULT_BLOCK_K
            and not flag_value("flash_attention_block_q")
            and not flag_value("flash_attention_block_k")
            and sq == sk and sq > 1024):
        if sq <= 4096:
            # measured v5e routing (GPT-2 cfg): at mid sequence lengths the
            # single-k-tile fast path (whole key range, q blocks shrunk to
            # keep the f32 logits tile at 4 MB) beats the online-softmax
            # multi-tile path — no m/l scratch round-trips or rescale
            # rounds (s=2048: 100.5k vs 96.1k tok/s; s=4096: 81.8k vs
            # 81.0k).
            block_q, block_k = max(2 ** 20 // sq, 128), sq
        else:
            # long sequences: keep the causal-skipping multi-tile path but
            # at (512, 2048) tiles — same 4 MB logits area, 4x fewer
            # online-softmax rescale rounds per q row than 1024x1024
            # (s=8192 b4: 61.4k vs 60.1k tok/s, 51.3% vs 50.3% MFU)
            block_q, block_k = 512, 2048
    block_q = flag_value("flash_attention_block_q") or block_q
    block_k = flag_value("flash_attention_block_k") or block_k
    bwd_block = flag_value("flash_attention_bwd_block") or bwd_block
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    bwd_block = _pick_block(sq, bwd_block) or block_q
    if dropout_p > 0.0 and (block_q % DROP_UNIT or block_k % DROP_UNIT
                            or bwd_block % DROP_UNIT):
        # non-canonical tile sizes key the PRNG mask on tile SHAPE: the
        # backward must then re-tile exactly like the forward (canonical
        # 512-unit draws lift this, letting fwd keep 1024 single-k tiles
        # while bwd runs its 512 VMEM-friendly ones). The unified block
        # must divide BOTH seq dims — pick from gcd(sq, sk), never the raw
        # min (which could silently truncate the key range when sq != sk)
        u = min(x for x in (block_q, block_k, bwd_block) if x)
        u = _pick_block(math.gcd(sq, sk), u)
        if not u:
            raise ValueError(
                f"dropout tiling: no common 128-aligned block divides both "
                f"seq_q={sq} and seq_k={sk}"
            )
        block_q = block_k = bwd_block = u
    hpg_chk, width_chk = _group_width(e // h if h else 1)
    if hpg_chk and sq * width_chk * 4 > MAX_BWD_SLAB_BYTES:
        raise ValueError(
            f"flash_attention_packed: seq_q={sq} exceeds the backward dq "
            f"slab budget (~{MAX_BWD_SLAB_BYTES // (width_chk * 4)} rows at "
            f"this head width) — use the layout-swapping flash_attention "
            f"or ring attention for longer sequences"
        )
    if not supports(sq, sk, h, e, block_q or 1, block_k or 1) \
            or not (block_q and block_k):
        raise ValueError(
            f"flash_attention_packed needs 128-aligned seq blocks and "
            f"128-lane head groups: seq_q={sq}, seq_k={sk}, e={e}, h={h}"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if bias is not None:
        bias = jnp.asarray(bias)
        if bias.ndim != 2 or bias.shape != (sq, sk):
            raise ValueError(
                f"packed kernel takes a shared (sq, sk) bias; got "
                f"{bias.shape} — use flash_attention for 4-D biases"
            )
        if bias.dtype == jnp.bool_:
            bias = jnp.where(bias, 0.0, NEG_INF).astype(jnp.float32)
        else:
            bias = bias.astype(jnp.float32)
    return _flash(q, k, v, bias, seed, h, float(scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret), dropout_p,
                  int(bwd_block))
