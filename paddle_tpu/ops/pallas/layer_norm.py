"""Fused LayerNorm as a Pallas TPU kernel (forward + fused backward).

TPU-native replacement for the reference's fused layernorm CUDA kernels
(``paddle/fluid/operators/fused/fused_layernorm_residual_dropout_bias.h`` and
the LN stages inside ``fused_attention_op.cu``): one VMEM pass per row block
computes mean/var/normalize/affine; the backward kernel recomputes the row
statistics (cheaper than storing them — LN is bandwidth-bound) and
accumulates dgamma/dbeta across row blocks in a revisited output block.

Rows are flattened to ``(rows, features)``; features must be lane-aligned
(multiple of 128) — callers fall back to the XLA path otherwise.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 256
LANES = 128


def _stats(x, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return xc, rstd


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    xc, rstd = _stats(x, eps)
    y = xc * rstd * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dgamma_ref, dbeta_ref, *, eps):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dgamma_ref[:] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[:] = jnp.zeros_like(dbeta_ref)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    gamma = g_ref[:].astype(jnp.float32)
    xc, rstd = _stats(x, eps)
    xhat = xc * rstd
    dxhat = dy * gamma
    mean_dxhat = jnp.mean(dxhat, axis=-1, keepdims=True)
    mean_dxhat_xhat = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dgamma_ref[:] = dgamma_ref[:] + jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbeta_ref[:] = dbeta_ref[:] + jnp.sum(dy, axis=0, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ln(x, gamma, beta, eps, block_rows, interpret):
    rows, feat = x.shape
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, feat), lambda i: (i, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, feat), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, gamma, beta)


def _ln_fwd(x, gamma, beta, eps, block_rows, interpret):
    return _ln(x, gamma, beta, eps, block_rows, interpret), (x, gamma)


def _ln_bwd(eps, block_rows, interpret, res, dy):
    x, gamma = res
    rows, feat = x.shape
    dx, dgamma, dbeta = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, feat), lambda i: (i, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, feat), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, feat), lambda i: (i, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
            pl.BlockSpec((1, feat), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct((1, feat), jnp.float32),
            jax.ShapeDtypeStruct((1, feat), jnp.float32),
        ],
        interpret=interpret,
    )(x, gamma, dy)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def supports(features):
    return features % LANES == 0


def fused_layer_norm(x, gamma, beta, eps=1e-5, interpret=None):
    """LayerNorm over the last axis. ``x``: (..., features); ``gamma``/``beta``:
    (features,). Returns the same shape/dtype as ``x``."""
    from . import interpret_requested

    if interpret is None:
        interpret = interpret_requested()
    feat = x.shape[-1]
    if not supports(feat):
        raise ValueError(f"fused_layer_norm needs features % {LANES} == 0, got {feat}")
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    x2 = x.reshape(rows, feat)
    # sublane-aligned row block; pad rows to a block multiple (padded rows
    # carry zero cotangents through the slice below, so grads are exact)
    block_rows = min(BLOCK_ROWS, -(-rows // 8) * 8)
    pad = -rows % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _ln(x2, gamma.reshape(1, feat), beta.reshape(1, feat),
              float(eps), int(block_rows), bool(interpret))
    out = out[:rows]
    return out.reshape(*lead, feat)
