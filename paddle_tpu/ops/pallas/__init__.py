"""Pallas TPU kernels — the hot fused ops.

TPU-native replacement for the reference's hand-written CUDA fused kernels
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``,
``fused_softmax_mask.cu.h``, fused layernorm inside
``fused_attention_op.cu``): here each fused op is a Pallas kernel tiled for
MXU/VMEM, with a custom VJP so the backward is fused too.

Capability gating is EXPLICIT (no silent fallbacks): :func:`is_available`
says whether the Mosaic TPU compile path exists for the current backend, and
``interpret_mode()`` lets tests run the same kernels interpreted on CPU.
"""
from __future__ import annotations

import os

import jax

_FORCE_INTERPRET = False


def interpret_requested() -> bool:
    """True when Pallas kernels should run in interpreter mode (CPU tests)."""
    return _FORCE_INTERPRET or os.environ.get("PADDLE_PALLAS_INTERPRET", "") == "1"


class interpret_mode:
    """Context manager forcing interpreter-mode Pallas (for CPU parity tests)."""

    def __enter__(self):
        global _FORCE_INTERPRET
        self._prev = _FORCE_INTERPRET
        _FORCE_INTERPRET = True
        return self

    def __exit__(self, *exc):
        global _FORCE_INTERPRET
        _FORCE_INTERPRET = self._prev
        return False


def is_available() -> bool:
    """Mosaic (compiled Pallas) needs a TPU backend; interpreter mode works
    anywhere.  ``axon`` is the tunnelled single-TPU platform the driver uses."""
    if interpret_requested():
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


from .flash_attention import flash_attention, flash_attention_cached  # noqa: E402,E501
from .layer_norm import fused_layer_norm  # noqa: E402

__all__ = [
    "flash_attention",
    "flash_attention_cached",
    "fused_layer_norm",
    "is_available",
    "interpret_mode",
    "interpret_requested",
]
