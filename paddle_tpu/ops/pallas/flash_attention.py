"""Flash attention as a Pallas TPU kernel (forward + fused backward).

TPU-native replacement for the reference CUDA fused attention
(``paddle/fluid/operators/fused/fused_attention_op.cu``, ``fmha_ref.h``):
blockwise online-softmax attention that never materializes the ``[b,h,s,s]``
logits in HBM.  The grid iterates ``(batch, head, q_block, k_block)`` with the
running ``(m, l, acc)`` state held in VMEM scratch across the innermost
k-block sweep — the canonical TPU flash schedule: both matmuls per tile hit
the MXU, softmax runs on the VPU, HBM traffic is O(s·d) not O(s²).

Backward is two fused kernels (dq swept over k-blocks; dk/dv swept over
q-blocks) recomputing p from the saved logsumexp — the FlashAttention-2
recurrence.

The logsumexp is stored sublane-oriented as ``(b, h, s, 8)`` (trailing dim
equal to the full array dim keeps the block legal for Mosaic while staying
16x smaller than a 128-lane broadcast); delta (= rowsum(do*o)) is never
materialized — the backward kernels recompute it per tile from the streamed
``o`` block.  head_dim is used unpadded (block dim = full array dim).

Attention dropout runs IN-KERNEL via the TPU hardware PRNG
(``pltpu.prng_seed`` / ``prng_random_bits``): every kernel (fwd, dq, dk/dv)
re-seeds per (batch, head, q_block, k_block) tile from the caller's seed, so
the three kernels regenerate the identical keep-mask without ever
materializing a ``[b,h,s,s]`` mask in HBM — the same design as the reference
CUDA kernel's in-kernel curand dropout
(``paddle/fluid/operators/fused/fused_attention_op.cu``). Dropout is applied
post-softmax: the l-normalizer accumulates the *undropped* p, the output
accumulates the dropped one. Backward identities (with ``P_d = P·M/keep``):
``delta = rowsum(dO∘O) = Σ_k P_d·dP_d`` still holds, so
``dS = P∘(dP·M/keep − delta)`` and ``dV = P_dᵀ·dO``. Hardware PRNG has no
interpret-mode lowering, so dropout requires a real TPU backend (the F.sdpa
router falls back to the einsum path on CPU).

Layout: public API takes paddle layout ``(batch, seq, heads, head_dim)``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Measured on v5e (GPT-2 shapes, d=64): 1024x1024 tiles are ~2x faster than
# 512x512 and ~9x faster than 256x256 at s=4096 (fwd+bwd), and beat XLA's
# fused einsum attention at s=1024 (102.6k vs 88.0k tok/s end-to-end GPT
# training). Bigger tiles exceed VMEM. Override via
# FLAGS_flash_attention_block_{q,k}.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
LANES = 128
STAT_LANES = 8  # sublane-oriented row-stat arrays
NEG_INF = -1e30


def _causal_mask(s, qi, ki, block_q, block_k, offset):
    """Bottom-right-aligned causal mask (matches the einsum path's
    ``tril(k=seq_k-seq_q)``): query row r attends keys <= r + offset where
    ``offset = seq_k - seq_q``."""
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(cols <= rows + offset, s, NEG_INF)


def _causal_run(qi, ki, block_q, block_k, offset):
    """Does this (q_block, k_block) tile contain any unmasked entry?"""
    return qi * block_q + block_q - 1 + offset >= ki * block_k


def _zero_masked_rows(p, stat):
    """Zero softmax rows whose running max (fwd) or saved lse (bwd) is
    still NEG_INF: a fully-masked causal query row (the sq > sk boundary
    landing inside a tile) has every logit at NEG_INF, so ``exp(s - stat)``
    collapses to exp(≈0) = 1 — a spurious uniform softmax. The contract
    for such rows is output 0 / lse NEG_INF / zero gradients."""
    return jnp.where(stat > NEG_INF * 0.5, p, 0.0)


def _dropout_mask(seed_ref, qi, ki, shape, dropout_p, head=None):
    """Regenerate the per-tile keep mask from the hardware PRNG. The tile
    coordinates are folded into the two user seed words (``prng_seed``
    accepts at most two scalars through this toolchain) so fwd/dq/dkv
    kernels — whatever their grid order — draw identical bits for the same
    (batch, head, q_block, k_block) tile: distinct tiles map to distinct
    seed pairs (qi, ki < 2^16; heads < 2^10). ``head`` is the static head
    index for kernels that unroll heads in-kernel (the packed layout);
    the layout-swapping kernels carry the head on grid axis 1."""
    bb = pl.program_id(0)
    hh = pl.program_id(1) if head is None else head
    pltpu.prng_seed(seed_ref[0] ^ (qi * 65536 + ki),
                    seed_ref[1] ^ (bb * 1024 + hh))
    return _keep_bits(shape, dropout_p)


def _keep_bits(shape, dropout_p):
    """Draw the keep mask for an already-seeded PRNG. 16 random bits per
    element suffice for the keep test (rate resolution 1/65536) and halve
    the PRNG work vs 32: draw half the sublanes as uint32, bitcast to
    uint16 (which doubles the sublane dim back). Compare in int32: the VPU
    has no 16-bit compare ("Target does not support this comparison"); the
    widening is cheap relative to PRNG."""
    bits = pltpu.bitcast(
        pltpu.prng_random_bits((shape[0] // 2, shape[1])), jnp.uint16
    )
    thr = min(int((1.0 - dropout_p) * 65536.0), 65535)
    return bits.astype(jnp.int32) < thr


def _logits(q_ref, k_ref, b_ref, qi, ki, scale, causal, block_q, block_k,
            offset):
    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    if b_ref is not None:
        s = s + b_ref[0, 0].astype(jnp.float32)
    if causal:
        s = _causal_mask(s, qi, ki, block_q, block_k, offset)
    return s


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k,
                offset, dropout_p):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = _causal_run(qi, ki, block_q, block_k, offset) if causal else (ki >= 0)

    @pl.when(run)
    def _body():
        s = _logits(q_ref, k_ref, b_ref, qi, ki, scale, causal, block_q,
                    block_k, offset)
        m_prev = m_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = _zero_masked_rows(jnp.exp(s - m_new), m_new)
        # l accumulates the UNdropped p (softmax normalizes pre-dropout);
        # only the value matmul sees the dropped probabilities.
        l_new = l_ref[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_mask(seed_ref, qi, ki, s.shape, dropout_p)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0, 0] = jnp.broadcast_to(
                m_ref[:, 0:1] + jnp.log(l_safe), lse_ref.shape[2:]
            )


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, o_ref,
                   lse_ref, dq_ref, dq_acc, *, scale, causal, block_q,
                   block_k, offset, dropout_p):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _causal_run(qi, ki, block_q, block_k, offset) if causal else (ki >= 0)

    @pl.when(run)
    def _body():
        s = _logits(q_ref, k_ref, b_ref, qi, ki, scale, causal, block_q,
                    block_k, offset)
        lse = lse_ref[0, 0][:, 0:1]
        p = _zero_masked_rows(jnp.exp(s - lse), lse)
        do = do_ref[0, 0]
        # delta = rowsum(do * o): recomputed per tile from the streamed o
        # block — elementwise O(block_q*d), far cheaper than materializing a
        # lane-broadcast (b,h,sq,128) delta array in HBM. With dropout this
        # equals Σ_k P_d·dP_d, exactly the softmax-jacobian rowsum needed.
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0, 0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0, 0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        if dropout_p > 0.0:
            keep = _dropout_mask(seed_ref, qi, ki, s.shape, dropout_p)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        ds = p * (dp - delta) * scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0, 0],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, o_ref,
                    lse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, offset, dropout_p):
    ki, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _causal_run(qi, ki, block_q, block_k, offset) if causal else (qi >= 0)

    @pl.when(run)
    def _body():
        s = _logits(q_ref, k_ref, b_ref, qi, ki, scale, causal, block_q,
                    block_k, offset)
        lse = lse_ref[0, 0][:, 0:1]
        p = _zero_masked_rows(jnp.exp(s - lse), lse)
        do = do_ref[0, 0]
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0, 0].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0, 0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        if dropout_p > 0.0:
            keep = _dropout_mask(seed_ref, qi, ki, s.shape, dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            p_d = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_d = p
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p_d.astype(do.dtype), do,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0, 0],
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bias_spec(bias, block_q, block_k, kv_major=False):
    """BlockSpec for an additive bias of shape (B|1, H|1, sq, sk), broadcasting
    over size-1 batch/head dims via the index map."""
    if bias is None:
        return None
    bb = bias.shape[0] > 1
    bh = bias.shape[1] > 1

    if kv_major:
        def imap(b, h, ki, qi):
            return (b if bb else 0, h if bh else 0, qi, ki)
    else:
        def imap(b, h, qi, ki):
            return (b if bb else 0, h if bh else 0, qi, ki)

    return pl.BlockSpec((1, 1, block_q, block_k), imap)


def _inject_none(kernel, *positions):
    """Adapt a kernel to a call signature missing some refs (seed / bias /
    lse) by inserting ``None`` at the given positions of the kernel's FULL
    signature (ascending insertion keeps later indices valid)."""

    def wrapped(*refs):
        refs = list(refs)
        for p in sorted(positions):
            refs.insert(p, None)
        return kernel(*refs)

    return wrapped


def _check_shapes(q, k, v, bias):
    b, h, sq, d = q.shape
    bk, hk, sk, dk = k.shape
    assert v.shape == k.shape, (v.shape, k.shape)
    assert (bk, hk, dk) == (b, h, d), (q.shape, k.shape)
    if bias is not None:
        assert bias.ndim == 4 and bias.shape[2:] == (sq, sk), bias.shape
        assert bias.shape[0] in (1, b) and bias.shape[1] in (1, h), bias.shape
    return b, h, sq, sk, d


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, bias, seed, scale, causal, block_q, block_k, interpret,
           need_dbias, dropout_p):
    # primal path (inference / no grad): skip the logsumexp output entirely
    return _flash_fwd_impl(q, k, v, bias, seed, scale, causal, block_q,
                           block_k, interpret, dropout_p, need_stats=False)


def _seed_spec(seed):
    # whole (2,) int32 seed in SMEM, identical for every grid step
    return None if seed is None else pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_fwd_impl(q, k, v, bias, seed, scale, causal, block_q, block_k,
                    interpret, dropout_p, need_stats=True):
    b, h, sq, sk, d = _check_shapes(q, k, v, bias)
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq

    def qmap(bb, hh, qi, ki):
        return (bb, hh, qi, 0)

    def kmap(bb, hh, qi, ki):
        return (bb, hh, ki, 0)

    in_specs = [
        _seed_spec(seed),
        pl.BlockSpec((1, 1, block_q, d), qmap),
        pl.BlockSpec((1, 1, block_k, d), kmap),
        pl.BlockSpec((1, 1, block_k, d), kmap),
        _bias_spec(bias, block_q, block_k),
    ]
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, dropout_p=dropout_p,
    )
    # full kernel signature: (seed, q, k, v, bias, o, lse, <scratch>)
    missing = []
    if seed is None:
        missing.append(0)
    if bias is None:
        missing.append(4)
    if need_stats:
        out_specs = [
            pl.BlockSpec((1, 1, block_q, d), qmap),
            pl.BlockSpec((1, 1, block_q, STAT_LANES),
                         lambda bb, hh, qi, ki: (bb, hh, qi, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, STAT_LANES), jnp.float32),
        ]
    else:
        missing.append(6)
        out_specs = pl.BlockSpec((1, 1, block_q, d), qmap)
        out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
    if missing:
        kernel = _inject_none(kernel, *missing)
    result = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[s for s in in_specs if s is not None],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * h * sq * sk * d * (0.5 if causal else 1.0)),
            bytes_accessed=int(2 * (q.size + k.size + v.size + q.size)),
            transcendentals=int(b * h * sq * sk),
        ),
    )(*[x for x in (seed, q, k, v, bias) if x is not None])
    return result


def _flash_fwd(q, k, v, bias, seed, scale, causal, block_q, block_k,
               interpret, need_dbias, dropout_p):
    out, lse = _flash_fwd_impl(q, k, v, bias, seed, scale, causal, block_q,
                               block_k, interpret, dropout_p, need_stats=True)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, need_dbias,
               dropout_p, res, g):
    q, k, v, bias, seed, out, lse = res
    b, h, sq, sk, d = _check_shapes(q, k, v, bias)
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq

    def qmap(bb, hh, qi, ki):
        return (bb, hh, qi, 0)

    def kmap(bb, hh, qi, ki):
        return (bb, hh, ki, 0)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, dropout_p=dropout_p,
    )
    # full kernel signature: (seed, q, k, v, bias, do, o, lse, dq, <scratch>)
    missing = ([0] if seed is None else []) + ([4] if bias is None else [])
    if missing:
        dq_kernel = _inject_none(dq_kernel, *missing)
    dq_specs = [
        _seed_spec(seed),                              # seed
        pl.BlockSpec((1, 1, block_q, d), qmap),        # q
        pl.BlockSpec((1, 1, block_k, d), kmap),        # k
        pl.BlockSpec((1, 1, block_k, d), kmap),        # v
        _bias_spec(bias, block_q, block_k),            # bias
        pl.BlockSpec((1, 1, block_q, d), qmap),        # do
        pl.BlockSpec((1, 1, block_q, d), qmap),        # o
        pl.BlockSpec((1, 1, block_q, STAT_LANES),
                     lambda bb, hh, qi, ki: (bb, hh, qi, 0)),  # lse
    ]
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=[s for s in dq_specs if s is not None],
        out_specs=pl.BlockSpec((1, 1, block_q, d), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*[x for x in (seed, q, k, v, bias, g, out, lse) if x is not None])

    # dk/dv sweep: grid (b, h, k_block, q_block) so the per-k-block
    # accumulators persist in scratch across the q sweep.
    def kv_qmap(bb, hh, ki, qi):
        return (bb, hh, qi, 0)

    def kv_kmap(bb, hh, ki, qi):
        return (bb, hh, ki, 0)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, dropout_p=dropout_p,
    )
    # full signature: (seed, q, k, v, bias, do, o, lse, dk, dv, <scratch>)
    missing = ([0] if seed is None else []) + ([4] if bias is None else [])
    if missing:
        dkv_kernel = _inject_none(dkv_kernel, *missing)
    dkv_specs = [
        _seed_spec(seed),                              # seed
        pl.BlockSpec((1, 1, block_q, d), kv_qmap),     # q
        pl.BlockSpec((1, 1, block_k, d), kv_kmap),     # k
        pl.BlockSpec((1, 1, block_k, d), kv_kmap),     # v
        _bias_spec(bias, block_q, block_k, kv_major=True),
        pl.BlockSpec((1, 1, block_q, d), kv_qmap),     # do
        pl.BlockSpec((1, 1, block_q, d), kv_qmap),     # o
        pl.BlockSpec((1, 1, block_q, STAT_LANES),
                     lambda bb, hh, ki, qi: (bb, hh, qi, 0)),  # lse
    ]
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk, nq),
        in_specs=[s for s in dkv_specs if s is not None],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), kv_kmap),
            pl.BlockSpec((1, 1, block_k, d), kv_kmap),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*[x for x in (seed, q, k, v, bias, g, out, lse) if x is not None])

    if bias is None:
        dbias = None
    elif not need_dbias:
        # constant mask (the common case): a symbolic-zero-like cheap
        # cotangent; no score matrix is ever materialized
        dbias = jnp.zeros_like(bias)
    else:
        # Real bias gradient: dS = P ⊙ (dO·Vᵀ − rowsum(dO⊙O)), reduced onto
        # the bias's broadcast shape. Computed with XLA ops from the saved
        # residuals — this materializes the [b,h,sq,sk] score block, the
        # unavoidable cost of a trainable dense bias (requested explicitly
        # via need_dbias; under jit, XLA additionally DCEs it when the
        # cotangent goes unused).
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        s = s + bias
        if causal:
            rows = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
            cols = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
            s = jnp.where((rows + offset >= cols)[None, None], s, NEG_INF)
        p = _zero_masked_rows(jnp.exp(s - lse[..., 0:1]), lse[..., 0:1])
        dp = jnp.einsum("bhqd,bhkd->bhqk", g, v,
                        preferred_element_type=jnp.float32)
        delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), -1)
        ds = p * (dp - delta[..., None])
        # reduce over the bias's broadcast (size-1) dims
        red = tuple(i for i in (0, 1) if bias.shape[i] == 1)
        dbias = jnp.sum(ds, axis=red, keepdims=True) if red else ds
        dbias = dbias.astype(bias.dtype)
    # integer seed gets a float0 cotangent (jax's tangent type for ints)
    dseed = None if seed is None else np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pick_block(seq, pref):
    """Largest lane-aligned block <= pref that divides seq (0 if none)."""
    b = min(pref, seq)
    b -= b % LANES
    while b >= LANES:
        if seq % b == 0:
            return b
        b -= LANES
    return 0


def supports(seq_q, seq_k, head_dim=None,
             block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Static shape gate: sequence lengths must tile into 128-aligned blocks.
    ``head_dim`` is accepted for signature stability but unconstrained — the
    kernels use it unpadded (block dim equals the full array dim, which
    Mosaic accepts for any size)."""
    return _pick_block(seq_q, block_q) > 0 and _pick_block(seq_k, block_k) > 0


# ---------------------------------------------------------------------------
# length-masked (cached) forward — serving prefill / chunked prefill / verify
# ---------------------------------------------------------------------------

def _cached_fwd_kernel(q_ref, k_ref, v_ref, qpos_ref, klen_ref, o_ref,
                       acc_ref, m_ref, l_ref, *, scale, block_k):
    """Online-softmax sweep with per-row validity from streamed positions:
    key slot j attends iff ``j <= q_pos[row]`` and ``j < kv_len[batch]`` —
    the LengthMask contract — so no dense bias ever reaches HBM."""
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s = jax.lax.dot_general(
        q_ref[0, 0], k_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    ) * scale
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    qpos = qpos_ref[0, 0][:, 0:1]
    valid = (cols <= qpos) & (cols < klen_ref[0, 0])
    s = jnp.where(valid, s, NEG_INF)
    m_prev = m_ref[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = _zero_masked_rows(jnp.exp(s - m_new), m_new)
    l_new = l_ref[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _flash_cached_impl(q, k, v, qpos, klen, scale, block_q, block_k,
                       interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k

    def qmap(bb, hh, qi, ki):
        return (bb, hh, qi, 0)

    def kmap(bb, hh, qi, ki):
        return (bb, hh, ki, 0)

    kernel = functools.partial(_cached_fwd_kernel, scale=scale,
                               block_k=block_k)
    return pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), qmap),
            pl.BlockSpec((1, 1, block_k, d), kmap),
            pl.BlockSpec((1, 1, block_k, d), kmap),
            pl.BlockSpec((1, 1, block_q, STAT_LANES),
                         lambda bb, hh, qi, ki: (bb, 0, qi, 0)),
            pl.BlockSpec((1, 1), lambda bb, hh, qi, ki: (bb, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), qmap),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * h * sq * sk * d),
            bytes_accessed=int(2 * (q.size + k.size + v.size + q.size)),
            transcendentals=int(b * h * sq * sk),
        ),
    )(q, k, v, qpos, klen)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_cached(q, k, v, qpos, klen, scale, block_q, block_k, interpret):
    return _flash_cached_impl(q, k, v, qpos, klen, scale, block_q, block_k,
                              interpret)


def _flash_cached_vjp_fwd(q, k, v, qpos, klen, scale, block_q, block_k,
                          interpret):
    out = _flash_cached_impl(q, k, v, qpos, klen, scale, block_q, block_k,
                             interpret)
    return out, ()


def _flash_cached_vjp_bwd(scale, block_q, block_k, interpret, res, g):
    raise NotImplementedError(
        "flash_attention_cached is inference-only (serving holds no "
        "gradients through the KV cache); train-time length masking goes "
        "through the blockwise-scan sdpa path")


_flash_cached.defvjp(_flash_cached_vjp_fwd, _flash_cached_vjp_bwd)


def supports_cached(seq_q, seq_k, head_dim=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Shape gate for the length-masked kernel: both sequence dims must tile
    into 128-aligned blocks (decode's seq_q=1 and sub-lane prefill chunks
    route to the blockwise XLA scan instead)."""
    return _pick_block(seq_q, block_q) > 0 and _pick_block(seq_k, block_k) > 0


def flash_attention_cached(q, k, v, q_pos, kv_len=None, *, scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                           interpret=None):
    """Length-masked flash attention over a static-shape KV cache.

    Args:
      q, k, v: ``(batch, seq, heads, head_dim)`` (paddle layout); ``k``/``v``
        are full cache buffers of ``max_len`` rows.
      q_pos: int32 ``(batch, seq_q)`` absolute cache position of each query
        row; key slot ``j`` attends iff ``j <= q_pos[b, i]``.
      kv_len: optional int32 ``(batch,)`` exclusive bound of valid cache
        rows (``None`` -> all ``seq_k`` rows writable-valid).

    Forward-only: serving's prefill / chunked-prefill / speculative-verify
    steps. Returns ``(batch, seq_q, heads, head_dim)``.
    """
    from ...framework.flags import flag_value
    from . import interpret_requested

    if interpret is None:
        interpret = interpret_requested()
    block_q = flag_value("flash_attention_block_q") or block_q
    block_k = flag_value("flash_attention_block_k") or block_k
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if not (block_q and block_k):
        raise ValueError(
            f"flash_attention_cached needs 128-aligned sequence blocks: "
            f"seq_q={sq}, seq_k={sk}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    qpos = jnp.broadcast_to(
        jnp.asarray(q_pos, jnp.int32)[:, None, :, None],
        (b, 1, sq, STAT_LANES))
    klen = (jnp.full((b, 1), sk, jnp.int32) if kv_len is None
            else jnp.asarray(kv_len, jnp.int32).reshape(b, 1))
    out = _flash_cached(qt, kt, vt, qpos, klen, float(scale), int(block_q),
                        int(block_k), bool(interpret))
    return jnp.swapaxes(out, 1, 2)


def flash_attention(q, k, v, bias=None, *, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None, bias_grad=True,
                    dropout_p=0.0, dropout_seed=None):
    """Blockwise flash attention.

    Args:
      q, k, v: ``(batch, seq, heads, head_dim)`` (paddle layout).
      bias: optional additive mask (bool masks are converted), shape
        ``(sq, sk)`` or ``(B|1, H|1, sq, sk)``.
      bias_grad: whether the backward computes the real bias gradient
        (dS reduced onto the bias shape). Correct-by-default; pass False
        for constant masks to guarantee the O(sq·sk) score matrix is never
        materialized in the backward (the F.sdpa wrapper does this
        automatically from ``mask.stop_gradient``).
      causal: bottom-right-aligned causal mask (row r attends keys
        ``<= r + sk - sq``, matching softmax-attention convention).
      scale: softmax scale; default ``1/sqrt(head_dim)``.
      dropout_p: attention-probability dropout rate, applied IN-KERNEL via
        the TPU hardware PRNG (no HBM mask). Requires ``dropout_seed`` and a
        compiled TPU backend (no interpret-mode lowering exists for the
        hardware PRNG). Deterministic given the seed.
      dropout_seed: ``(2,)`` int32 array; fwd and bwd kernels re-derive the
        identical keep mask from it per (batch, head, q_block, k_block) tile.

    Returns ``(batch, seq_q, heads, head_dim)``.
    """
    from ...framework.flags import flag_value
    from . import interpret_requested

    if interpret is None:
        interpret = interpret_requested()
    dropout_p = float(dropout_p)
    if dropout_p > 0.0:
        if interpret:
            raise ValueError(
                "in-kernel attention dropout needs the TPU hardware PRNG; "
                "no interpret-mode lowering exists (use the einsum path)"
            )
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed")
        if bias is not None and bias_grad:
            raise ValueError(
                "bias_grad with attention dropout is unsupported: the XLA "
                "dbias recompute cannot regenerate the in-kernel PRNG mask "
                "(pass bias_grad=False for constant masks)"
            )
        if q.shape[2] >= 1024:
            # the per-tile seed fold packs the head index into 10 bits;
            # beyond that distinct heads would silently share keep-masks
            raise ValueError(
                f"in-kernel dropout supports < 1024 heads (got {q.shape[2]})"
            )
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape(2)
    else:
        seed = None
    block_q = flag_value("flash_attention_block_q") or block_q
    block_k = flag_value("flash_attention_block_k") or block_k
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, block_q)
    block_k = _pick_block(sk, block_k)
    if not (block_q and block_k):
        raise ValueError(
            f"flash_attention needs 128-aligned sequence blocks: seq_q={sq}, "
            f"seq_k={sk}"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    # head_dim needs no padding: the kernels' block last dim equals the full
    # array dim, which Mosaic accepts for any d (lanes padded only in VMEM)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if bias is not None:
        bias = jnp.asarray(bias)
        if bias.ndim not in (2, 4):
            raise ValueError(
                f"flash_attention mask must be (sq, sk) or (B|1, H|1, sq, sk); "
                f"got shape {bias.shape} — a 3-D mask is ambiguous"
            )
        if bias.dtype == jnp.bool_:
            bias = jnp.where(bias, 0.0, NEG_INF).astype(jnp.float32)
        else:
            bias = bias.astype(jnp.float32)
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    out = _flash(qt, kt, vt, bias, seed, float(scale), bool(causal),
                 int(block_q), int(block_k), bool(interpret),
                 bool(bias_grad) and bias is not None, dropout_p)
    return jnp.swapaxes(out, 1, 2)
