"""Pallas fused LM-head + softmax cross-entropy ("flash CE").

Reference capability: ``paddle/phi/kernels/gpu/cross_entropy_kernel.cu`` +
``c_softmax_with_cross_entropy_op.cu`` (fused softmax-CE). The XLA-scan
fallback in ``ops/fused.py`` already avoids materializing the full
``[tokens, vocab]`` logits in HBM, but XLA cannot fuse a matmul with its
consumer reductions on TPU: each scan chunk writes its ``[chunk, vocab]``
f32 logits tile to HBM and the while-body fusions read it back (measured
on v5e, GPT-2 124M b16 s1024: ~31 ms/step of while self-time + 6.6 ms of
dW-carry dynamic-update-slice + 4.4 ms of select-reduce — pure HBM
round-trips on top of ~27 ms of near-roofline matmuls).

These kernels keep every logits tile in VMEM:

 - forward: grid (token_block, vocab_block), online logsumexp in scratch
   (running m / l), label logit picked via iota-compare — loss and lse
   written once per token block;
 - backward dx: grid (token_block, vocab_block), recomputes the logits
   tile, forms ``dl = (softmax - onehot) * g`` in registers, accumulates
   ``dl @ W`` in scratch, writes dx once;
 - backward dW (+db): grid (vocab_block, token_block), accumulates
   ``dl^T @ x`` (and ``colsum(dl)``) in scratch, writes once — the scan's
   154 MB f32 dW carry never exists.

Measured outcome (v5e, those shapes): the op is VPU-EXP-BOUND — ~824M f32
exps per forward put an ~8-9 ms floor under any implementation, and the
XLA scan's matmuls already run at ~96% MXU with the while-body overlapped
against them. Forward: Pallas 14.5 ms vs scan 15.7 (blocks 1024x1024).
Fwd+bwd: Pallas 41 vs scan 37 — the split dx/dW backward recomputes the
logits twice where the scan shares one compute per chunk. The scan
therefore remains the hardware default; these kernels are opt-in
(FLAGS_enable_flash_ce) and the interpret-mode default so they stay
correctness-tested. They win where the scan cannot run (e.g. a future
sequence-parallel CE that must fuse a collective per tile).

Arbitrary shapes: tokens pad to the token block (pad g = 0 so padded rows
contribute nothing), vocab pads to the vocab block with masked columns
(``s = -inf`` → p = 0, dl = 0, dW pad rows = 0), sliced off outside.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

DEFAULT_BLOCK_N = 1024
DEFAULT_BLOCK_V = 512


def _cols(vi, shape, block_v):
    return vi * block_v + jax.lax.broadcasted_iota(jnp.int32, shape, 1)


def _logits(x_ref, w_ref, b_ref, vi, block_v, v_real, pad_v):
    s = jax.lax.dot_general(
        x_ref[...], w_ref[...],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    if b_ref is not None:
        s = s + b_ref[...].astype(jnp.float32)
    if pad_v:
        s = jnp.where(_cols(vi, s.shape, block_v) < v_real, s, NEG_INF)
    return s


def _ce_fwd_kernel(x_ref, w_ref, b_ref, y_ref, loss_ref, lse_ref,
                   m_sc, l_sc, pk_sc, *, block_v, v_real, pad_v):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        pk_sc[...] = jnp.zeros_like(pk_sc)

    s = _logits(x_ref, w_ref, b_ref, vi, block_v, v_real, pad_v)
    m_prev = m_sc[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    l_sc[...] = (l_sc[...] * jnp.exp(m_prev - m_new)
                 + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True))
    m_sc[...] = m_new
    eq = _cols(vi, s.shape, block_v) == y_ref[...]
    pk_sc[...] = pk_sc[...] + jnp.sum(jnp.where(eq, s, 0.0), axis=-1,
                                      keepdims=True)

    @pl.when(vi == nv - 1)
    def _fin():
        lse = m_sc[...] + jnp.log(l_sc[...])
        lse_ref[...] = lse
        loss_ref[...] = lse - pk_sc[...]


def _dl(x_ref, w_ref, b_ref, y_ref, g_ref, lse_ref, vi, block_v, v_real,
        pad_v):
    s = _logits(x_ref, w_ref, b_ref, vi, block_v, v_real, pad_v)
    p = jnp.exp(s - lse_ref[...])
    eq = _cols(vi, s.shape, block_v) == y_ref[...]
    return (p - eq.astype(jnp.float32)) * g_ref[...]


def _ce_dx_kernel(x_ref, w_ref, b_ref, y_ref, g_ref, lse_ref, dx_ref,
                  dx_sc, *, block_v, v_real, pad_v):
    vi = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(vi == 0)
    def _init():
        dx_sc[...] = jnp.zeros_like(dx_sc)

    dl = _dl(x_ref, w_ref, b_ref, y_ref, g_ref, lse_ref, vi, block_v,
             v_real, pad_v)
    dx_sc[...] = dx_sc[...] + jax.lax.dot_general(
        dl.astype(w_ref.dtype), w_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(vi == nv - 1)
    def _fin():
        dx_ref[...] = dx_sc[...].astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, b_ref, y_ref, g_ref, lse_ref, dw_ref,
                  db_ref, dw_sc, db_sc, *, block_v, v_real, pad_v):
    vi, ni = pl.program_id(0), pl.program_id(1)
    nn = pl.num_programs(1)

    @pl.when(ni == 0)
    def _init():
        dw_sc[...] = jnp.zeros_like(dw_sc)
        if db_sc is not None:
            db_sc[...] = jnp.zeros_like(db_sc)

    dl = _dl(x_ref, w_ref, b_ref, y_ref, g_ref, lse_ref, vi, block_v,
             v_real, pad_v)
    dw_sc[...] = dw_sc[...] + jax.lax.dot_general(
        dl.astype(x_ref.dtype), x_ref[...],
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    if db_sc is not None:
        db_sc[...] = db_sc[...] + jnp.sum(dl, axis=0, keepdims=True)

    @pl.when(ni == nn - 1)
    def _fin():
        dw_ref[...] = dw_sc[...].astype(dw_ref.dtype)
        if db_ref is not None:
            db_ref[...] = db_sc[...]


def _inject(kernel, *positions):
    def wrapped(*refs):
        refs = list(refs)
        for p in sorted(positions):
            refs.insert(p, None)
        return kernel(*refs)

    return wrapped


def _pad_dim(a, axis, size, value=0.0):
    pad = (-a.shape[axis]) % size
    if not pad:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


def _prep(x, w, b, y, g, block_n, block_v):
    """Pad tokens/vocab to block multiples; reshape 1-D per-token arrays to
    (N, 1) lane-scalar blocks."""
    n, hdim = x.shape
    v = w.shape[0]
    xp = _pad_dim(x, 0, block_n)
    wp = _pad_dim(w, 0, block_v)
    yp = _pad_dim(y.reshape(n, 1).astype(jnp.int32), 0, block_n)
    bp = None if b is None else _pad_dim(b.reshape(1, v), 1, block_v)
    gp = (None if g is None
          else _pad_dim(g.reshape(n, 1).astype(jnp.float32), 0, block_n))
    return xp, wp, bp, yp, gp, xp.shape[0], wp.shape[0]


def supports(hidden_size):
    """H must be lane-tileable; tokens/vocab pad internally."""
    return hidden_size % 128 == 0


def ce_forward(x, w, b, y, *, block_n=DEFAULT_BLOCK_N,
               block_v=DEFAULT_BLOCK_V, interpret=False):
    """Returns (loss, lse), each shape (tokens,) f32."""
    n, hdim = x.shape
    v = w.shape[0]
    xp, wp, bp, yp, _, np_, vp = _prep(x, w, b, y, None, block_n, block_v)
    nn, nv = np_ // block_n, vp // block_v
    kernel = functools.partial(
        _ce_fwd_kernel, block_v=block_v, v_real=v, pad_v=(vp != v))
    if bp is None:
        kernel = _inject(kernel, 2)
    in_specs = [
        pl.BlockSpec((block_n, hdim), lambda ni, vi: (ni, 0)),      # x
        pl.BlockSpec((block_v, hdim), lambda ni, vi: (vi, 0)),      # w
        None if bp is None else
        pl.BlockSpec((1, block_v), lambda ni, vi: (0, vi)),         # b
        pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),         # y
    ]
    loss, lse = pl.pallas_call(
        kernel,
        grid=(nn, nv),
        in_specs=[sp for sp in in_specs if sp is not None],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=int(2 * np_ * vp * hdim),
            bytes_accessed=int(x.size * 2 + nn * w.size * 2),
            transcendentals=int(np_ * vp),
        ),
    )(*[a for a in (xp, wp, bp, yp) if a is not None])
    return loss[:n, 0], lse[:n, 0]


def ce_backward(x, w, b, y, g, lse, *, block_n=DEFAULT_BLOCK_N,
                block_v=DEFAULT_BLOCK_V, interpret=False):
    """Returns (dx, dw, db) — db is None when b is None. ``g`` is the
    per-token upstream gradient (already zeroed at ignored labels)."""
    n, hdim = x.shape
    v = w.shape[0]
    xp, wp, bp, yp, gp, np_, vp = _prep(x, w, b, y, g, block_n, block_v)
    lp = _pad_dim(lse.reshape(n, 1).astype(jnp.float32), 0, block_n)
    nn, nv = np_ // block_n, vp // block_v
    pad_v = vp != v

    dx_kernel = functools.partial(
        _ce_dx_kernel, block_v=block_v, v_real=v, pad_v=pad_v)
    if bp is None:
        dx_kernel = _inject(dx_kernel, 2)
    dx_specs = [
        pl.BlockSpec((block_n, hdim), lambda ni, vi: (ni, 0)),      # x
        pl.BlockSpec((block_v, hdim), lambda ni, vi: (vi, 0)),      # w
        None if bp is None else
        pl.BlockSpec((1, block_v), lambda ni, vi: (0, vi)),         # b
        pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),         # y
        pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),         # g
        pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),         # lse
    ]
    dx = pl.pallas_call(
        dx_kernel,
        grid=(nn, nv),
        in_specs=[sp for sp in dx_specs if sp is not None],
        out_specs=pl.BlockSpec((block_n, hdim), lambda ni, vi: (ni, 0)),
        out_shape=jax.ShapeDtypeStruct((np_, hdim), x.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, hdim), jnp.float32)],
        interpret=interpret,
    )(*[a for a in (xp, wp, bp, yp, gp, lp) if a is not None])

    dw_kernel = functools.partial(
        _ce_dw_kernel, block_v=block_v, v_real=v, pad_v=pad_v)
    if bp is None:
        # no bias: drop b input AND the db output/scratch
        def dw_wrapped(x_ref, w_ref, y_ref, g_ref, lse_ref, dw_ref, dw_sc):
            return dw_kernel(x_ref, w_ref, None, y_ref, g_ref, lse_ref,
                             dw_ref, None, dw_sc, None)
        dw_k = dw_wrapped
    else:
        dw_k = dw_kernel
    dw_specs = [
        pl.BlockSpec((block_n, hdim), lambda vi, ni: (ni, 0)),      # x
        pl.BlockSpec((block_v, hdim), lambda vi, ni: (vi, 0)),      # w
        None if bp is None else
        pl.BlockSpec((1, block_v), lambda vi, ni: (0, vi)),         # b
        pl.BlockSpec((block_n, 1), lambda vi, ni: (ni, 0)),         # y
        pl.BlockSpec((block_n, 1), lambda vi, ni: (ni, 0)),         # g
        pl.BlockSpec((block_n, 1), lambda vi, ni: (ni, 0)),         # lse
    ]
    dw_out_specs = [pl.BlockSpec((block_v, hdim), lambda vi, ni: (vi, 0))]
    dw_out_shape = [jax.ShapeDtypeStruct((vp, hdim), w.dtype)]
    scratch = [pltpu.VMEM((block_v, hdim), jnp.float32)]
    if bp is not None:
        dw_out_specs.append(pl.BlockSpec((1, block_v),
                                         lambda vi, ni: (0, vi)))
        dw_out_shape.append(jax.ShapeDtypeStruct((1, vp), jnp.float32))
        scratch.append(pltpu.VMEM((1, block_v), jnp.float32))
    out = pl.pallas_call(
        dw_k,
        grid=(nv, nn),
        in_specs=[sp for sp in dw_specs if sp is not None],
        out_specs=dw_out_specs,
        out_shape=dw_out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*[a for a in (xp, wp, bp, yp, gp, lp) if a is not None])
    if bp is None:
        dw = out if not isinstance(out, (tuple, list)) else out[0]
        db = None
    else:
        dw, db2 = out
        db = db2[0, :v]
    return dx[:n], dw[:v], db
