"""Tensor creation ops (reference ``python/paddle/tensor/creation.py``)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor, to_tensor
from .dispatch import op


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else (default or dtypes.get_default_dtype())


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = (
            dtypes.get_default_dtype()
            if isinstance(fill_value, float)
            else ("int64" if isinstance(fill_value, (int, bool)) else None)
        )
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


@op("zeros_like")
def _zeros_like_raw(x):
    return jnp.zeros_like(x)


def zeros_like(x, dtype=None, name=None):
    t = _zeros_like_raw(x)
    return t.astype(dtype) if dtype is not None else t


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones(x._value.shape, _dt(dtype, x._value.dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full(x._value.shape, fill_value, _dt(dtype, x._value.dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else dtypes.get_default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v

    return Tensor(
        jnp.logspace(val(start), val(stop), int(val(num)), base=val(base), dtype=_dt(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    arrays = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[a._value for a in arrays], indexing="ij")
    return [Tensor(o) for o in outs]


@op("diag")
def _diag_raw(x, offset=0):
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    if padding_value != 0 and x.ndim == 1:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, x._value.dtype)
        out = base + (jnp.diag(x._value, k=offset) - jnp.diag(jnp.zeros(x.shape[0], x._value.dtype), k=offset))
        return Tensor(out)
    return _diag_raw(x, offset=offset)


@op("diagflat")
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@op("tril")
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@op("triu")
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), _dt(dtype)))


@op("assign")
def assign(x):
    return jnp.asarray(x)


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    return Tensor(real._value + 1j * imag._value)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    from ..framework.tensor import Parameter
    from ..nn.initializer import _apply_initializer

    value = _apply_initializer(default_initializer, shape, _dt(dtype), is_bias=is_bias)
    return Parameter(value, name=name)
