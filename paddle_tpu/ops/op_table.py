"""Declarative op table — the source of truth for the differentiable-op
API surface and its gradient-check specs.

Reference: the yaml op registry ``paddle/phi/api/yaml/legacy_api.yaml``
(+ backward yamls) generating API and grad rules; SURVEY §7 keeps "yaml
retained as the source of truth". TPU-native form: op *implementations* are
jax-traced functions (their grad rule IS jax.vjp), so what the table
declares is the part yaml declared that still matters here — the public
signature, which inputs are differentiable, the numeric domain each input
must be drawn from, and the finite-difference tolerances. The OpTest sweep
(``tests/test_op_grad_sweep.py``) is generated from this table, mirroring
the reference's per-op ``check_grad`` coverage.

Entry fields:
    api:     dotted path under the public surface ("ops.tanh", "F.relu",
             "Tensor.abs" is not used — methods alias the same ops)
    inputs:  tuple of input specs; each is (shape, domain) where domain is
             one of f / fp / unit / gt1 / sym / spd / prob / int:<n> / bool
             (int:/bool inputs are non-differentiable and fixed)
    kwargs:  static attributes
    rtol/atol/delta: finite-difference tolerances (defaults 1e-2/1e-3/1e-3)
    only:    indices of differentiable inputs to check (default: all float)
"""
from __future__ import annotations

OPS = []


def _op(api, inputs, kwargs=None, rtol=1e-2, atol=1e-3, delta=1e-3,
        only=None, out_reduce=False):
    OPS.append(dict(api=api, inputs=inputs, kwargs=kwargs or {},
                    rtol=rtol, atol=atol, delta=delta, only=only,
                    out_reduce=out_reduce))


S = (3, 4)          # default small shape
V = (6,)            # vector

# --- elementwise unary: full real domain -----------------------------------
for name in [
    "abs", "asinh", "atan", "cos", "cosh", "erf", "exp",
    "expm1", "neg", "sin", "sinh", "square", "tan", "tanh",
]:
    _op(f"ops.{name}", ((S, "f"),))
_op("ops.abs", ((S, "fp"),))            # away from the |x| kink at 0
_op("ops.atan2", ((S, "fp"), (S, "fp")))

# --- positive / restricted domains ------------------------------------------
for name in ["log", "log2", "log10", "log1p", "sqrt", "rsqrt", "reciprocal",
             "digamma", "lgamma"]:
    _op(f"ops.{name}", ((S, "fp"),))
_op("ops.acos", ((S, "unit"),))
_op("ops.asin", ((S, "unit"),))
_op("ops.atanh", ((S, "unit"),))
_op("ops.acosh", ((S, "gt1"),))
_op("ops.logit", ((S, "unit"),), kwargs=dict(eps=0.0))
_op("ops.erfinv", ((S, "unit"),))
_op("ops.cumprod", ((V, "fp"),), kwargs=dict(dim=0))
_op("ops.logsumexp", ((S, "f"),))
_op("ops.logaddexp", ((S, "f"), (S, "f")))

# --- binary elementwise ------------------------------------------------------
_op("ops.add", ((S, "f"), (S, "f")))
_op("ops.subtract", ((S, "f"), (S, "f")))
_op("ops.multiply", ((S, "f"), (S, "f")))
_op("ops.divide", ((S, "f"), (S, "fp")))
_op("ops.maximum", ((S, "f"), (S, "f2")))
_op("ops.minimum", ((S, "f"), (S, "f2")))
_op("ops.fmax", ((S, "f"), (S, "f2")))
_op("ops.fmin", ((S, "f"), (S, "f2")))
_op("ops.pow", ((S, "fp"), (S, "fp")))
_op("ops.hypot", ((S, "fp"), (S, "fp")))
_op("ops.copysign", ((S, "fp"), (S, "fp")), only=(0,))
_op("ops.lerp", ((S, "f"), (S, "f"), (S, "unit")))
_op("ops.nextafter", ((S, "f"), (S, "f")), only=())

# --- reductions --------------------------------------------------------------
_op("ops.sum", ((S, "f"),))
_op("ops.sum", ((S, "f"),), kwargs=dict(axis=1))
_op("ops.mean", ((S, "f"),))
_op("ops.mean", ((S, "f"),), kwargs=dict(axis=0, keepdim=True))
_op("ops.prod", ((S, "fp"),))
_op("ops.max", ((S, "funique"),))
_op("ops.min", ((S, "funique"),))
_op("ops.amax", ((S, "funique"),))
_op("ops.nansum", ((S, "f"),))
_op("ops.nanmean", ((S, "f"),))
_op("ops.std", ((S, "f"),), rtol=2e-2)
_op("ops.var", ((S, "f"),), rtol=2e-2)
_op("ops.trace", ((S, "f"),))
_op("ops.cumsum", ((S, "f"),), kwargs=dict(axis=1))
_op("ops.median", ((V, "funique"),), rtol=3e-2)
_op("ops.quantile", ((V, "funique"),), kwargs=dict(q=0.5), rtol=3e-2)

# --- linalg ------------------------------------------------------------------
M33 = (3, 3)
_op("ops.matmul", ((S, "f"), ((4, 5), "f")))
_op("ops.matmul", ((S, "f"), (S, "f")), kwargs=dict(transpose_y=True))
_op("ops.bmm", (((2, 3, 4), "f"), ((2, 4, 3), "f")))
_op("ops.dot", ((V, "f"), (V, "f")))
_op("ops.mv", ((S, "f"), ((4,), "f")))
_op("ops.outer", ((V, "f"), ((4,), "f")))
_op("ops.inner", ((S, "f"), ((5, 4), "f")))
_op("ops.kron", (((2, 2), "f"), ((2, 2), "f")))
_op("ops.addmm", ((M33, "f"), (M33, "f"), (M33, "f")))
_op("ops.inverse", ((M33, "spd"),), rtol=3e-2, atol=5e-3)
_op("ops.det", ((M33, "spd"),), rtol=3e-2)
_op("ops.slogdet", ((M33, "spd"),), rtol=3e-2, only=(0,))
_op("ops.cholesky", ((M33, "spd"),), rtol=3e-2, atol=5e-3)
_op("ops.solve", ((M33, "spd"), (M33, "f")), rtol=3e-2, atol=5e-3)
_op("ops.triangular_solve", ((M33, "trilpd"), (M33, "f")),
    rtol=3e-2, atol=5e-3)
_op("ops.matrix_power", ((M33, "f"),), kwargs=dict(n=2))
_op("ops.multi_dot", (((3, 4), "f"), ((4, 2), "f")))
_op("ops.einsum_ij_jk", (((3, 4), "f"), ((4, 2), "f")))
_op("ops.pinv", ((M33, "spd"),), rtol=5e-2, atol=1e-2)

# --- manipulation ------------------------------------------------------------
_op("ops.reshape", ((S, "f"),), kwargs=dict(shape=[4, 3]))
_op("ops.transpose", ((S, "f"),), kwargs=dict(perm=[1, 0]))
_op("ops.flatten", (((2, 3, 4), "f"),))
_op("ops.squeeze", (((3, 1, 4), "f"),), kwargs=dict(axis=1))
_op("ops.unsqueeze", ((S, "f"),), kwargs=dict(axis=0))
_op("ops.concat2", ((S, "f"), (S, "f")), kwargs=dict(axis=0))
_op("ops.stack2", ((S, "f"), (S, "f")), kwargs=dict(axis=0))
_op("ops.split_first", (((4, 4), "f"),), kwargs=dict(num_or_sections=2))
_op("ops.tile", ((S, "f"),), kwargs=dict(repeat_times=[2, 1]))
_op("ops.expand", (((1, 4), "f"),), kwargs=dict(shape=[3, 4]))
_op("ops.flip", ((S, "f"),), kwargs=dict(axis=[0]))
_op("ops.roll", ((S, "f"),), kwargs=dict(shifts=1))
_op("ops.rot90", ((S, "f"),))
_op("ops.moveaxis", (((2, 3, 4), "f"),), kwargs=dict(source=0, destination=2))
_op("ops.tril", ((S, "f"),))
_op("ops.triu", ((S, "f"),))
_op("ops.diag", ((V, "f"),))
_op("ops.diagonal", ((M33, "f"),))
_op("ops.diagflat", ((V, "f"),))
_op("ops.pad2d", ((S, "f"),), kwargs=dict(pad=[1, 1, 0, 2]))
_op("ops.gather", ((S, "f"), ((2,), "int:3")), kwargs=dict(axis=0))
_op("ops.index_select", ((S, "f"), ((2,), "int:3")), kwargs=dict(axis=0))
_op("ops.take_along_axis", ((S, "f"), ((3, 1), "int:4")), kwargs=dict(axis=1))
_op("ops.gather_nd", ((S, "f"), ((2, 2), "int:3")))
_op("ops.masked_fill", ((S, "f"), (S, "bool")), kwargs=dict(value=0.5))
_op("ops.where3", ((S, "bool"), (S, "f"), (S, "f")))
_op("ops.clip", ((S, "f"),), kwargs=dict(min=-0.5, max=0.5))
_op("ops.repeat_interleave", ((V, "f"),), kwargs=dict(repeats=2))
_op("ops.index_sample", ((S, "f"), ((3, 2), "int:4")))
_op("ops.getitem_slice", ((S, "f"),))
_op("ops.multiplex2", ((S, "f"), (S, "f")))

# --- activations (functional) ------------------------------------------------
for name in ["relu", "relu6", "elu", "selu", "celu", "gelu", "silu",
             "sigmoid", "softplus", "softsign", "mish", "tanhshrink",
             "log_sigmoid", "hardswish", "hardsigmoid", "leaky_relu",
             "hardtanh"]:
    _op(f"F.{name}", ((S, "fnz"),))
_op("ops.stanh", ((S, "f"),))
_op("F.softmax", ((S, "f"),))
_op("F.log_softmax", ((S, "f"),))
_op("F.softshrink", ((S, "fnz"),), kwargs=dict(threshold=0.1))
_op("F.hardshrink", ((S, "fnz"),), kwargs=dict(threshold=0.1))
_op("F.thresholded_relu", ((S, "fnz"),), kwargs=dict(threshold=0.3))
_op("F.prelu", ((S, "fnz"), ((1,), "unit")))
_op("F.glu", (((3, 4), "f"),))
_op("F.maxout", (((1, 4, 2, 2), "funique"),), kwargs=dict(groups=2))
_op("F.normalize", ((S, "fp"),))

# --- losses ------------------------------------------------------------------
_op("F.mse_loss", ((S, "f"), (S, "f")))
_op("F.l1_loss", ((S, "f"), (S, "gt1")))  # disjoint ranges: |x-y| kink
_op("F.smooth_l1_loss", ((S, "f"), (S, "f2")), kwargs=dict(delta=0.5))
_op("F.huber_loss", ((S, "f"), (S, "f2")), kwargs=dict(delta=0.5))
_op("F.kl_div", ((S, "logunit"), (S, "unit")), only=(0,))
_op("F.binary_cross_entropy", ((S, "unit"), (S, "unit")), only=(0,))
_op("F.binary_cross_entropy_with_logits", ((S, "f"), (S, "unit")), only=(0,))
_op("F.cross_entropy_labels", (((4, 5), "f"), ((4, 1), "int:5")), only=(0,))
_op("F.nll_loss", (((4, 5), "logunit"), ((4,), "int:5")), only=(0,))
_op("F.square_error_cost", ((S, "f"), (S, "f2")))
_op("F.log_loss", ((S, "unit"), (S, "unit")), only=(0,))
_op("F.margin_ranking_loss", ((V, "f"), (V, "f2"), (V, "sign")), only=(0, 1))
_op("F.cosine_embedding_loss", (((2, 4), "f"), ((2, 4), "f2"), ((2,), "sign")),
    only=(0, 1), rtol=2e-2)
_op("F.triplet_margin_loss", ((S, "f"), (S, "f2"), (S, "f3")), rtol=2e-2)
_op("F.hinge_embedding_loss", ((S, "fnz"), (S, "sign")), only=(0,))
_op("F.sigmoid_focal_loss", ((S, "f"), (S, "unit")), only=(0,), rtol=2e-2)
_op("F.softmax_with_cross_entropy", (((4, 5), "f"), ((4, 1), "int:5")),
    only=(0,))
_op("F.fused_linear_cross_entropy", (((6, 4), "f"), ((5, 4), "f"),
                                     ((6,), "int:5")), only=(0, 1))

# --- nn functional (structured) ---------------------------------------------
_op("F.linear", (((3, 4), "f"), ((4, 5), "f"), ((5,), "f")))
_op("F.conv2d", (((1, 2, 5, 5), "f"), ((3, 2, 3, 3), "f")), rtol=2e-2)
_op("F.conv1d", (((1, 2, 8), "f"), ((3, 2, 3), "f")), rtol=2e-2)
_op("F.conv2d_transpose", (((1, 2, 4, 4), "f"), ((2, 3, 3, 3), "f")),
    rtol=2e-2)
_op("F.avg_pool2d", (((1, 2, 4, 4), "f"),), kwargs=dict(kernel_size=2))
_op("F.max_pool2d", (((1, 2, 4, 4), "funique"),), kwargs=dict(kernel_size=2))
_op("F.adaptive_avg_pool2d", (((1, 2, 4, 4), "f"),), kwargs=dict(output_size=2))
_op("F.layer_norm_w", (((3, 4), "f"), ((4,), "fp"), ((4,), "f")), rtol=2e-2)
_op("F.embedding", (((3,), "int:5"), ((5, 4), "f")), only=(1,))
_op("F.dropout_eval", ((S, "f"),))
_op("F.unfold", (((1, 2, 4, 4), "f"),), kwargs=dict(kernel_sizes=2))
_op("F.interpolate_nearest", (((1, 2, 4, 4), "f"),), only=(0,))
_op("F.pixel_shuffle", (((1, 4, 2, 2), "f"),), kwargs=dict(upscale_factor=2))
_op("F.grid_sample", (((1, 1, 4, 4), "f"), ((1, 2, 2, 2), "unit")),
    rtol=3e-2, atol=5e-3)
_op("F.scaled_dot_product_attention",
    (((1, 4, 2, 4), "f"), ((1, 4, 2, 4), "f2"), ((1, 4, 2, 4), "f3")),
    kwargs=dict(training=False), rtol=2e-2)


# --- round-3 widening: manipulation / search --------------------------------
_op("ops.where", ((S, "bool"), (S, "f"), (S, "f2")), only=(1, 2))
_op("ops.sort", ((V, "funique"),))
_op("ops.topk", ((V, "funique"),), kwargs=dict(k=3))
_op("ops.scatter", (((5, 3), "f"), ((2,), "int:5"), ((2, 3), "f2")),
    only=(0, 2))
_op("ops.put_along_axis", (((4, 3), "f"), ((2, 3), "int:4"), ((2, 3), "f2")),
    kwargs=dict(axis=0), only=(0, 2))
_op("ops.fill_diagonal_", ((S, "f"),), kwargs=dict(value=0.5))
_op("ops.pad", ((S, "f"),), kwargs=dict(pad=[1, 1, 0, 2], mode="constant"))
# as_complex is NOT swept: the FD harness scalarizes via a real cast that
# discards the imaginary channel — it has a dedicated both-channel gradient
# test in tests/test_op_grads.py instead

# --- round-3 widening: math tails -------------------------------------------
_op("ops.frac", ((S, "f"),))
_op("ops.nan_to_num", ((S, "f"),))
_op("ops.deg2rad", ((S, "f"),))
_op("ops.rad2deg", ((S, "f"),))
_op("ops.cov", (((3, 6), "f"),))
_op("ops.dist", ((S, "fnz"), (S, "f2")), rtol=2e-2)

# --- round-3 widening: linalg decompositions --------------------------------
_op("ops.qr", (((3, 3), "spd"),), rtol=3e-2, atol=5e-3)
_op("ops.eigh", (((3, 3), "spd"),), rtol=3e-2, atol=5e-3)
_op("ops.cholesky_solve", (((3, 2), "f"), ((3, 3), "trilpd")), rtol=3e-2,
    atol=5e-3)

# --- round-3 widening: norms + functional tails ------------------------------
_op("F.group_norm", (((2, 4, 3, 3), "f"),), kwargs=dict(num_groups=2),
    rtol=2e-2)
_op("F.instance_norm", (((2, 3, 4, 4), "f"),), rtol=2e-2)
_op("F.batch_norm", (((2, 3, 4, 4), "f"), ((3,), "f2"), ((3,), "fp"),
                     ((3,), "fp"), ((3,), "f3")),
    kwargs=dict(training=False), only=(0, 3, 4), rtol=2e-2)
_op("F.cosine_similarity", (((3, 4), "fnz"), ((3, 4), "f2")), rtol=2e-2)
_op("F.fold", (((1, 8, 4), "f"),),
    kwargs=dict(output_sizes=[4, 4], kernel_sizes=2, strides=2))


# --- low-precision (bf16 / fp16) gradient axis ------------------------------
# Mirrors the reference OpTest's per-dtype check_grad registrations
# (``unittests/op_test.py:1851``: fp16/bf16 kernels are checked with
# loosened per-dtype tolerances against an fp32 reference). Here every
# table entry is additionally swept in bfloat16 AND float16
# (tests/test_op_grad_sweep_lowp.py): the op runs end-to-end in the compute
# dtype and its analytic gradient is compared, at low-precision-representable
# input points, against the fp32 analytic gradient (itself validated against
# finite differences by the main sweep).
#
# Defaults (relative to lowp eps: bf16 2^-8, fp16 2^-10):
LOWP_DEFAULT = {
    "bfloat16": dict(rtol=6e-2, atol=1e-2),
    "float16": dict(rtol=2e-2, atol=4e-3),
}
# Entries below DEVIATE from the default — False skips the dtype with the
# documented reason, a dict loosens tolerances for ops whose condition
# number amplifies the representation error. Keyed by table api name
# (duplicate entries share the key).
LOWP = {
    # XLA lowers these decompositions/solves through fp32-only routines on
    # CPU/TPU; low-precision inputs would silently upcast, testing nothing
    "ops.inverse": False,
    "ops.det": False,
    "ops.slogdet": False,
    "ops.cholesky": False,
    "ops.solve": False,
    "ops.triangular_solve": False,
    "ops.cholesky_solve": False,
    "ops.pinv": False,
    "ops.qr": False,
    "ops.eigh": False,
    "ops.matrix_power": False,
}
