"""Random ops (reference ``python/paddle/tensor/random.py``).

All draws split the global Generator key (framework/random.py), so they are
deterministic under paddle.seed and stay traceable under the jit path (the key
is part of the functionalized state)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework import random as rnd
from ..framework.tensor import Tensor
from .creation import _shape_list, _dt


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rnd.next_key(), _shape_list(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else rnd.next_key()
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return Tensor(jax.random.uniform(key, _shape_list(shape), _dt(dtype), minval=mn, maxval=mx))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    return x.set_value(uniform(x.shape, x.dtype, min, max, seed))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rnd.next_key(), _shape_list(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(rnd.next_key(), sh) * s + m)
    sh = _shape_list(shape) if shape is not None else []
    return Tensor(jax.random.normal(rnd.next_key(), sh) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    return x.set_value(jax.random.normal(rnd.next_key(), tuple(x.shape), x._value.dtype) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.key(seed) if seed else rnd.next_key()
    return Tensor(jax.random.normal(key, _shape_list(shape), _dt(dtype)) * std + mean)


def randint(low=0, high=None, shape=[1], dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(rnd.next_key(), _shape_list(shape), low, high).astype(
            dtypes.convert_dtype(dtype)
        )
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or "int64")


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(rnd.next_key(), n).astype(dtypes.convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    v = x._value
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(rnd.next_key(), logits, axis=-1, shape=(*v.shape[:-1], num_samples) if v.ndim > 1 else (num_samples,))
        if v.ndim > 1:
            out = out.reshape(*v.shape[:-1], num_samples)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(rnd.next_key(), v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    return Tensor(
        jax.random.bernoulli(rnd.next_key(), x._value).astype(x._value.dtype)
    )


def poisson(x, name=None):
    return Tensor(jax.random.poisson(rnd.next_key(), x._value).astype(x._value.dtype))


def exponential_(x, lam=1.0, name=None):
    return x.set_value(jax.random.exponential(rnd.next_key(), tuple(x.shape), x._value.dtype) / lam)


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else count
    p = prob._value if isinstance(prob, Tensor) else prob
    return Tensor(jax.random.binomial(rnd.next_key(), c, p).astype(jnp.int64))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return Tensor(jnp.exp(jax.random.normal(rnd.next_key(), _shape_list(shape or [])) * std + mean))
