"""Op surface aggregation + Tensor method patching.

Mirrors the reference's approach of assembling ``paddle.*`` tensor functions
from per-theme modules (``python/paddle/tensor/__init__.py``) and
monkey-patching them as Tensor methods
(``fluid/dygraph/varbase_patch_methods.py``)."""
from __future__ import annotations

from ..framework.tensor import Tensor
from .dispatch import OP_REGISTRY, ensure_tensor, op
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from . import random  # noqa: F401
from .random import (  # noqa: F401
    rand,
    randn,
    randint,
    randint_like,
    randperm,
    uniform,
    normal,
    standard_normal,
    bernoulli,
    multinomial,
    poisson,
)

from . import creation, math, manipulation, logic, linalg, search, stat  # noqa: F401
from . import fused  # noqa: F401


# --------------------------------------------------------------------------
# Tensor method patching
# --------------------------------------------------------------------------

import types as _types

_METHODS = {}
for _mod in (creation, math, manipulation, logic, linalg, search, stat):
    for _name in dir(_mod):
        if _name.startswith("_") or not _name[0].islower():
            continue
        _fn = getattr(_mod, _name)
        if callable(_fn) and not isinstance(_fn, (type, _types.ModuleType)):
            _METHODS.setdefault(_name, _fn)
_METHODS["einsum"] = einsum
for _name in ("uniform_", "normal_", "exponential_", "bernoulli", "multinomial"):
    _METHODS[_name] = getattr(random, _name)

_SKIP = {"is_tensor", "create_parameter", "meshgrid", "broadcast_tensors", "ensure_tensor", "op"}
for _name, _fn in _METHODS.items():
    if _name in _SKIP or hasattr(Tensor, _name):
        continue
    Tensor._patch_method(_name, _fn)

# `abs`/`all` etc shadow builtins in module scope but are fine as methods.
Tensor._patch_method("pow", lambda self, y: math.pow_(self, y))
Tensor._patch_method("mean", math.mean)
Tensor._patch_method("scale", math.scale)
Tensor._patch_method("add_n", lambda self, xs: add_n([self] + list(xs)))


def add_n(inputs, name=None):
    """paddle.add_n — sum of a tensor list (reference math.py add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    out = inputs[0]
    for t in inputs[1:]:
        out = math.add(out, t)
    return out


# in-place arithmetic variants (reference exposes add_/subtract_/scale_ etc.)
def _make_inplace(fn):
    def inplace(self, *a, **k):
        return self._rebind(fn(self, *a, **k))

    return inplace


for _n, _f in (
    ("add_", math.add),
    ("subtract_", math.subtract),
    ("multiply_", math.multiply),
    ("divide_", math.divide),
    ("clip_", math.clip),
    ("scale_", math.scale),
    ("floor_", math.floor),
    ("ceil_", math.ceil),
    ("exp_", math.exp),
    ("sqrt_", math.sqrt),
    ("rsqrt_", math.rsqrt),
    ("reciprocal_", math.reciprocal),
    ("round_", math.round),
    ("tanh_", math.tanh),
    ("abs_", math.abs),
    ("remainder_", math.remainder),
    ("pow_", math.pow_),
):
    Tensor._patch_method(_n, _make_inplace(_f))


def fill_(self, value):
    import jax.numpy as jnp

    self._value = jnp.full_like(self._value, value)
    return self


def zero_(self):
    return fill_(self, 0)


Tensor._patch_method("fill_", fill_)
Tensor._patch_method("zero_", zero_)

# ---------------------------------------------------------------- dunders ---

_BINOPS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(y, x) if isinstance(y, Tensor) else math.add(x, y),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: math.subtract(ensure_tensor(y, like=x), x),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: math.multiply(x, y),
    "__truediv__": math.divide,
    "__rtruediv__": lambda x, y: math.divide(ensure_tensor(y, like=x), x),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": lambda x, y: math.floor_divide(ensure_tensor(y, like=x), x),
    "__mod__": math.remainder,
    "__pow__": math.pow_,
    "__rpow__": lambda x, y: math.pow_(ensure_tensor(y, like=x), x),
    "__matmul__": math.matmul,
    "__rmatmul__": lambda x, y: math.matmul(ensure_tensor(y), x),
    "__eq__": math.equal,
    "__ne__": math.not_equal,
    "__lt__": math.less_than,
    "__le__": math.less_equal,
    "__gt__": math.greater_than,
    "__ge__": math.greater_equal,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
}
for _n, _f in _BINOPS.items():
    Tensor._patch_method(_n, _f)

Tensor._patch_method("__neg__", math.neg)
Tensor._patch_method("__abs__", math.abs)
Tensor._patch_method("__invert__", logic.bitwise_not)
