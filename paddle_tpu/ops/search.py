"""Search / sort ops (reference ``python/paddle/tensor/search.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor
from .dispatch import op, ensure_tensor


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    v = x._value
    if axis is None:
        v = v.reshape(-1)
        axis = 0
    out = jnp.argmax(v, axis=axis, keepdims=keepdim)
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    v = x._value
    if axis is None:
        v = v.reshape(-1)
        axis = 0
    out = jnp.argmin(v, axis=axis, keepdims=keepdim)
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    v = x._value
    idx = jnp.argsort(v, axis=axis, descending=descending, stable=stable)
    return Tensor(idx.astype(jnp.int64))


@op("sort")
def _sort_raw(x, axis=-1, descending=False):
    return jnp.sort(x, axis=axis, descending=descending)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    return _sort_raw(x, axis=axis, descending=descending)


def _lax_topk(x, k, axis):
    xm = jnp.moveaxis(x, axis, -1)
    v, i = jax.lax.top_k(xm, k)
    return jnp.moveaxis(v, -1, axis), jnp.moveaxis(i, -1, axis)


@op("topk_op")
def _topk_raw(x, k=1, axis=-1, largest=True):
    if largest:
        v, i = _lax_topk(x, k, axis)
    else:
        v, i = _lax_topk(-x, k, axis)
        v = -v
    return v, i.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    v, i = _topk_raw(x, k=int(k), axis=int(axis), largest=largest)
    i.stop_gradient = True
    return v, i


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    v, i = _topk_raw(x, k=int(k), axis=int(axis), largest=False)
    from . import manipulation as man

    ax = int(axis)
    vk = man._getitem(v, tuple([slice(None)] * (ax % x.ndim) + [k - 1]))
    ik = man._getitem(i, tuple([slice(None)] * (ax % x.ndim) + [k - 1]))
    if keepdim:
        vk = man.unsqueeze(vk, ax)
        ik = man.unsqueeze(ik, ax)
    ik.stop_gradient = True
    return vk, ik


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    import scipy.stats as _st  # available via scipy dep of jax

    a = np.asarray(x._value)
    m = _st.mode(a, axis=axis, keepdims=keepdim)
    idx = np.argmax(a == (m.mode if keepdim else np.expand_dims(m.mode, axis)), axis=axis)
    if keepdim:
        idx = np.expand_dims(idx, axis)
    return Tensor(jnp.asarray(m.mode)), Tensor(jnp.asarray(idx, np.int64))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    sv = sorted_sequence._value
    vv = values._value
    if sv.ndim == 1:
        out = jnp.searchsorted(sv, vv, side=side)
    else:
        out = jnp.stack(
            [jnp.searchsorted(sv[i], vv[i], side=side) for i in range(sv.shape[0])]
        )
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_sample(x, index):
    from .manipulation import index_sample as _is

    return _is(x, index)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms

    return _ms(x, mask)


def where(condition, x=None, y=None, name=None):
    from .manipulation import where as _w

    return _w(condition, x, y)


def nonzero(x, as_tuple=False):
    from .manipulation import nonzero as _nz

    return _nz(x, as_tuple)
