"""Statistics ops (reference ``python/paddle/tensor/stat.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .dispatch import op
from .math import _axis


@op("var_op")
def _var_raw(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var_raw(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@op("std_op")
def _std_raw(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std_raw(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@op("median_op")
def _median_raw(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return _median_raw(x, axis=axis, keepdim=keepdim)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.nanmedian(x._value, axis=_axis(axis), keepdims=keepdim))


@op("quantile_op")
def _quantile_raw(x, q=0.5, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim, method=interpolation)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    if isinstance(q, Tensor):
        q = q._value
    elif isinstance(q, (list, tuple)):
        q = jnp.asarray(q)
    return _quantile_raw(x, q=q, axis=_axis(axis), keepdim=keepdim, interpolation=interpolation)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    if isinstance(q, Tensor):
        q = q._value
    return Tensor(
        jnp.nanquantile(x._value, q, axis=_axis(axis), keepdims=keepdim, method=interpolation)
    )


@op("nansum")
def _nansum_raw(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = _nansum_raw(x, axis=_axis(axis), keepdim=keepdim)
    return out.astype(dtype) if dtype is not None else out


@op("nanmean")
def _nanmean_raw(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return _nanmean_raw(x, axis=_axis(axis), keepdim=keepdim)
