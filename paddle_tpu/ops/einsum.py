"""einsum (reference ``python/paddle/tensor/einsum.py``) — delegates to
jnp.einsum, which XLA maps onto MXU contractions."""
from __future__ import annotations

import jax.numpy as jnp

from .dispatch import op


@op("einsum")
def _einsum_raw(*operands, equation=None):
    return jnp.einsum(equation, *operands, precision=None)


def einsum(equation, *operands):
    if not isinstance(equation, str):
        # paddle also allows einsum(op0, op1, ..., equation=...)
        raise TypeError("first argument must be the equation string")
    return _einsum_raw(*operands, equation=equation)
