"""Linear algebra (reference ``python/paddle/tensor/linalg.py``; kernels
``paddle/phi/kernels/*matrix*``, backed by cusolver on GPU — here jax.lax.linalg
which lowers to XLA's TPU-native decompositions)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .dispatch import op
from . import math as _math

matmul = _math.matmul
dot = _math.dot


@op("norm_op")
def _norm_raw(x, p=2.0, axis=None, keepdim=False):
    if p == "fro" or p is None:
        p = 2.0
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if isinstance(axis, tuple) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p if p != 2.0 else None, axis=axis, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    p = 2.0 if p is None or p == "fro" else p
    return _norm_raw(x, p=p, axis=axis, keepdim=keepdim)


@op("dist")
def dist(x, y, p=2.0):
    d = x - y
    d = d.reshape(-1)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@op("cond_op")
def _cond_raw(x, p=None):
    if p is None or p == 2:
        s = jnp.linalg.svd(x, compute_uv=False)
        return s[..., 0] / s[..., -1]
    return jnp.linalg.cond(x, p=p)


def cond(x, p=None, name=None):
    return _cond_raw(x, p=p)


@op("inverse")
def inverse(x):
    return jnp.linalg.inv(x)


inv = inverse


@op("pinv")
def _pinv_raw(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _pinv_raw(x, rcond=rcond, hermitian=hermitian)


@op("det")
def det(x):
    return jnp.linalg.det(x)


@op("slogdet")
def slogdet(x):
    s, l = jnp.linalg.slogdet(x)
    return jnp.stack([s, l])


@op("cholesky")
def _cholesky_raw(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return _cholesky_raw(x, upper=upper)


@op("cholesky_solve")
def _cholesky_solve_raw(x, y, upper=False):
    import jax.scipy.linalg as jsl

    return jsl.cho_solve((y, not upper), x)


def cholesky_solve(x, y, upper=False, name=None):
    return _cholesky_solve_raw(x, y, upper=upper)


@op("solve")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@op("triangular_solve")
def _triangular_solve_raw(x, y, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    return jsl.solve_triangular(x, y, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return _triangular_solve_raw(x, y, upper=upper, transpose=transpose, unitriangular=unitriangular)


@op("lstsq_sol")
def _lstsq_raw(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol = _lstsq_raw(x, y, rcond=rcond)
    xv, yv = x._value, y._value
    res = jnp.sum((xv @ sol._value - yv) ** 2, axis=-2)
    rank = jnp.linalg.matrix_rank(xv)
    sv = jnp.linalg.svd(xv, compute_uv=False)
    return sol, Tensor(res), Tensor(rank), Tensor(sv)


@op("qr_op")
def _qr_raw(x, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


def qr(x, mode="reduced", name=None):
    if mode == "r":
        return Tensor(jnp.linalg.qr(x._value, mode="r"))
    return _qr_raw(x, mode=mode)


@op("svd_op")
def _svd_raw(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2).conj()


def svd(x, full_matrices=False, name=None):
    return _svd_raw(x, full_matrices=full_matrices)


def eig(x, name=None):
    w, v = jnp.linalg.eig(jnp.asarray(x._value))
    return Tensor(w), Tensor(v)


@op("eigh_op")
def _eigh_raw(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, symmetrize_input=(UPLO == "L"))
    return w, v


def eigh(x, UPLO="L", name=None):
    return _eigh_raw(x, UPLO=UPLO)


def eigvals(x, name=None):
    return Tensor(jnp.linalg.eigvals(jnp.asarray(x._value)))


@op("eigvalsh_op")
def _eigvalsh_raw(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return _eigvalsh_raw(x, UPLO=UPLO)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(x._value, rtol=tol))


@op("matrix_power")
def _matrix_power_raw(x, n=1):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return _matrix_power_raw(x, n=int(n))


@op("multi_dot")
def _multi_dot_raw(*xs):
    return jnp.linalg.multi_dot(xs)


def multi_dot(x, name=None):
    return _multi_dot_raw(*x)


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl

    lu_, piv = jsl.lu_factor(x._value)
    info = Tensor(jnp.zeros(x.shape[:-2], jnp.int32))
    piv_t = Tensor((piv + 1).astype(jnp.int32))
    if get_infos:
        return Tensor(lu_), piv_t, info
    return Tensor(lu_), piv_t


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack ``lu``'s packed factorization into (P, L, U) (reference
    ``tensor/linalg.py lu_unpack``; pivots are 1-based sequential row
    transpositions, matching ``lu``'s output)."""
    lu_v = lu_data._value
    piv = lu_pivots._value.astype(jnp.int32) - 1   # back to 0-based
    m, n = lu_v.shape[-2], lu_v.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_v[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_v.dtype)
        U = jnp.triu(lu_v[..., :k, :])
    if unpack_pivots:
        def perm_of(pv):
            def body(p, i):
                j = pv[i]
                pi, pj = p[i], p[j]
                p = p.at[i].set(pj).at[j].set(pi)
                return p, None

            p0 = jnp.arange(m, dtype=jnp.int32)
            p, _ = jax.lax.scan(body, p0, jnp.arange(pv.shape[-1]))
            return p

        flat_piv = piv.reshape((-1, piv.shape[-1]))
        perms = jnp.stack([perm_of(pv) for pv in flat_piv], 0).reshape(
            piv.shape[:-1] + (m,))
        P = jax.nn.one_hot(perms, m, dtype=lu_v.dtype)
        # rows of P: P[perm[i], i] = 1 so that A = P @ L @ U
        P = jnp.swapaxes(P, -1, -2)
    outs = [Tensor(v) if v is not None else None for v in (P, L, U)]
    return tuple(outs)


def corrcoef(x, rowvar=True, name=None):
    return Tensor(jnp.corrcoef(x._value, rowvar=rowvar))


@op("cov_op")
def _cov_raw(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights._value if fweights is not None else None
    aw = aweights._value if aweights is not None else None
    return _cov_raw(x, rowvar=rowvar, ddof=ddof, fweights=fw, aweights=aw)


@op("histogram_op")
def _histogram_raw(x, bins=100, min=0, max=0):
    rng = None if (min == 0 and max == 0) else (min, max)
    h, _ = jnp.histogram(x, bins=bins, range=rng)
    return h


def histogram(input, bins=100, min=0, max=0, name=None):
    return Tensor(_histogram_raw.raw(input._value, bins=bins, min=min, max=max).astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._value if weights is not None else None
    return Tensor(jnp.bincount(x._value, weights=w, minlength=minlength, length=None))
