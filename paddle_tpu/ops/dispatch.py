"""Op dispatch: the eager kernel-invocation path.

TPU-native replacement for the reference dispatch stack
(generated dygraph functions → ``paddle::experimental::*`` API →
``phi::KernelFactory::SelectKernelOrThrowError`` ``phi/core/kernel_factory.h:261``
→ per-backend phi kernel): here every op is ONE jax-traceable python function
lowered by XLA, so backend selection, dtype keys, and stream scheduling all
disappear. What remains is exactly the part the reference generates per-op
(``eager/auto_code_generator/final_state_generator/eager_gen.py:883``):
unwrap tensors, decide whether grad is needed, run the forward, and record a
GradNode whose backward fn is the op's ``jax.vjp``.

Ops are declared with :func:`op` on a raw-jnp forward; the wrapper handles
Tensor↔array conversion + autograd recording. The registry doubles as the
"op table" (analogue of phi's yaml op list) for introspection and codegen.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..autograd.engine import Edge, GradNode, is_grad_enabled, leaf_edge as _leaf_edge
from ..framework import dtype as dtypes
from ..framework.tensor import Tensor

OP_REGISTRY = {}

# set by paddle_tpu.amp.auto_cast: callable (op_name, fwd) -> fwd implementing
# O1 per-op dtype policy (reference imperative/amp_auto_cast.h AutoCastGuard)
AMP_HOOK = None

# set by paddle_tpu.static.program_guard: callable (name, fwd, args, kwargs)
# that records an op node when any arg is a symbolic static.Variable and
# returns the output Variable(s), or None to run eagerly (reference static
# mode appends OpDescs to the current BlockDesc instead of executing)
STATIC_RECORDER = None


def _needs_grad(t: Tensor) -> bool:
    return (not t.stop_gradient) and dtypes.is_differentiable(t.dtype)


def apply_op(name, fwd, args, static_kwargs):
    """Run ``fwd(*arrays, **static_kwargs)`` eagerly with autograd recording.

    ``args`` may mix Tensors, raw arrays and python scalars; only Tensor args
    participate in autograd.
    """
    if AMP_HOOK is not None:
        # applied BEFORE recording so static Programs capture the autocast
        # wrapper too (reference static AMP rewrites the program with cast
        # ops — here the recorded fwd simply IS the autocasting fn)
        fwd = AMP_HOOK(name, fwd)
    if STATIC_RECORDER is not None:
        recorded = STATIC_RECORDER(name, fwd, args, static_kwargs)
        if recorded is not None:
            return recorded
    vals = []
    tensor_pos = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            vals.append(a._value)
            tensor_pos.append(i)
        else:
            vals.append(a)

    diff_pos = (
        [i for i in tensor_pos if _needs_grad(args[i])] if is_grad_enabled() else []
    )

    if not diff_pos:
        out = fwd(*vals, **static_kwargs)
        _check_nan_inf(name, out)
        return _wrap_outputs(out, node=None)

    diff_vals = [vals[i] for i in diff_pos]

    def closed(*dv):
        vv = list(vals)
        for p, v in zip(diff_pos, dv):
            vv[p] = v
        return fwd(*vv, **static_kwargs)

    primal_out, vjp_fn = jax.vjp(closed, *diff_vals)
    edges = [_leaf_edge(args[i]) for i in diff_pos]
    multi = isinstance(primal_out, (tuple, list))
    outs = list(primal_out) if multi else [primal_out]
    out_info = [(o.shape, o.dtype) for o in outs]
    # fwd_closed + primal Tensor refs enable create_graph=True (double
    # backward): the traversal re-records this vjp over (primals, cotangents)
    node = GradNode(name, vjp_fn, edges, out_info, multi,
                    fwd_closed=closed, inputs=[args[i] for i in diff_pos])
    _check_nan_inf(name, primal_out)
    return _wrap_outputs(primal_out, node=node)


def apply_nondiff_op(name, fwd, args, static_kwargs=None):
    """Dispatch for ops with non-differentiable (bool/int) outputs:
    participates in static Program recording like apply_op, but never
    records a GradNode and skips the AMP per-op dtype policy (comparisons
    are dtype-neutral; the reference registers compare/logical kernels
    without grad ops and outside the amp op lists)."""
    static_kwargs = static_kwargs or {}
    if STATIC_RECORDER is not None:
        recorded = STATIC_RECORDER(name, fwd, args, static_kwargs)
        if recorded is not None:
            return recorded
    vals = [a._value if isinstance(a, Tensor) else a for a in args]
    return _wrap_outputs(fwd(*vals, **static_kwargs), node=None)


def _check_nan_inf(name, out):
    """FLAGS_check_nan_inf debug scan (reference
    ``framework/details/nan_inf_utils_detail.cc``; eager version
    ``eager/nan_inf_utils.cc``). Eager-mode only — traced values are skipped
    (inside jit the GradScaler's found_inf path covers it)."""
    from ..framework.flags import flag_value

    if not flag_value("check_nan_inf"):
        return
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        if isinstance(o, jax.core.Tracer) or not hasattr(o, "dtype"):
            continue
        if jnp.issubdtype(o.dtype, jnp.floating):
            if not bool(jnp.isfinite(o).all()):
                raise FloatingPointError(
                    f"Operator {name} output contains Inf/Nan "
                    f"(FLAGS_check_nan_inf is set)."
                )


def _wrap_outputs(out, node):
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    wrapped = []
    for slot, o in enumerate(outs):
        t = Tensor(o, stop_gradient=node is None)
        if node is not None:
            t._grad_node = node
            t._out_slot = slot
        wrapped.append(t)
    return tuple(wrapped) if multi else wrapped[0]


def op(name=None, inplace_of=None):
    """Declare an op from a raw-jnp forward function.

    The decorated function's positional args may be Tensors (autograd inputs);
    keyword args are static attributes (baked into the trace, like reference
    OpMaker attrs).
    """

    def deco(fwd):
        opname = name or fwd.__name__

        @functools.wraps(fwd)
        def wrapper(*args, **kwargs):
            return apply_op(opname, fwd, args, kwargs)

        wrapper.raw = fwd
        wrapper.op_name = opname
        OP_REGISTRY[opname] = wrapper
        return wrapper

    return deco


def ensure_tensor(x, dtype=None, like=None):
    """Coerce scalars / arrays to Tensor, broadcasting dtype like paddle:
    python scalar operands adopt the tensor operand's dtype."""
    if isinstance(x, Tensor):
        return x
    if like is not None and isinstance(x, (int, float, bool)):
        return Tensor(jnp.asarray(x, like.dtype), stop_gradient=True)
    return Tensor(jnp.asarray(x, dtype), stop_gradient=True)
