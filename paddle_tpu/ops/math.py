"""Elementwise & reduction math ops (reference ``python/paddle/tensor/math.py``;
kernels in ``paddle/phi/kernels/``). Every op is a jnp forward lowered by XLA —
elementwise chains fuse into surrounding matmuls on the MXU automatically."""
from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor
from .dispatch import apply_nondiff_op, ensure_tensor, op

# ---------------------------------------------------------------- binary ----


def _binop(name, fn):
    raw = op(name)(fn)

    def api(x, y, name=None):
        x = ensure_tensor(x, like=y if isinstance(y, Tensor) else None)
        y = ensure_tensor(y, like=x)
        return raw(x, y)

    api.__name__ = name
    api.raw = fn
    return api


add = _binop("add", lambda x, y: jnp.add(x, y))
subtract = _binop("subtract", lambda x, y: jnp.subtract(x, y))
multiply = _binop("multiply", lambda x, y: jnp.multiply(x, y))
divide = _binop("divide", lambda x, y: jnp.true_divide(x, y))
floor_divide = _binop("floor_divide", lambda x, y: jnp.floor_divide(x, y))
remainder = _binop("remainder", lambda x, y: jnp.remainder(x, y))
mod = remainder
floor_mod = remainder
pow_ = _binop("elementwise_pow", lambda x, y: jnp.power(x, y))
maximum = _binop("maximum", lambda x, y: jnp.maximum(x, y))
minimum = _binop("minimum", lambda x, y: jnp.minimum(x, y))
fmax = _binop("fmax", lambda x, y: jnp.fmax(x, y))
fmin = _binop("fmin", lambda x, y: jnp.fmin(x, y))
atan2 = _binop("atan2", lambda x, y: jnp.arctan2(x, y))
hypot = _binop("hypot", lambda x, y: jnp.hypot(x, y))
logaddexp = _binop("logaddexp", lambda x, y: jnp.logaddexp(x, y))
nextafter = _binop("nextafter", lambda x, y: jnp.nextafter(x, y))
copysign = _binop("copysign", lambda x, y: jnp.copysign(x, y))
heaviside = _binop("heaviside", lambda x, y: jnp.heaviside(x, y))
gcd = _binop("gcd", lambda x, y: jnp.gcd(x, y))
lcm = _binop("lcm", lambda x, y: jnp.lcm(x, y))


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return pow_(x, y)


def divide_no_nan(x, y):
    return Tensor(jnp.where(y._value == 0, 0, x._value / y._value))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale._value if isinstance(scale, Tensor) else scale

    @op("scale")
    def _scale(xv):
        if bias_after_scale:
            return xv * s + bias
        return (xv + bias) * s

    out = _scale(x)
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


# --------------------------------------------------------------- unary ------


def _unop(name, fn):
    return op(name)(fn)


exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", lambda x: lax.rsqrt(x))
square = _unop("square", jnp.square)
abs = _unop("abs", jnp.abs)  # noqa: A001
sign = _unop("sign", jnp.sign)
ceil = _unop("ceil", jnp.ceil)
floor = _unop("floor", jnp.floor)
round = _unop("round", jnp.round)  # noqa: A001
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda x: x - jnp.trunc(x))
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
reciprocal = _unop("reciprocal", jnp.reciprocal)
neg = _unop("neg", jnp.negative)
erf = _unop("erf", jax.scipy.special.erf)
erfinv = _unop("erfinv", jax.scipy.special.erfinv)
lgamma = _unop("lgamma", jax.scipy.special.gammaln)
digamma = _unop("digamma", jax.scipy.special.digamma)
i0 = _unop("i0", jax.scipy.special.i0)
i0e = _unop("i0e", jax.scipy.special.i0e)
i1 = _unop("i1", jax.scipy.special.i1)
i1e = _unop("i1e", jax.scipy.special.i1e)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)
deg2rad = _unop("deg2rad", jnp.deg2rad)
rad2deg = _unop("rad2deg", jnp.rad2deg)
exponent = None  # not in reference API


def isnan(x, name=None):
    return Tensor(jnp.isnan(x._value))


def isinf(x, name=None):
    return Tensor(jnp.isinf(x._value))


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(x._value))


@op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@op("clip")
def _clip_raw(x, min=None, max=None):
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return _clip_raw(x, min=mn, max=mx)


@op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@op("logit")
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@op("multiplex")
def _multiplex_raw(*args):
    index = args[-1]
    ins = jnp.stack(args[:-1], axis=0)
    return jnp.take_along_axis(
        ins, index.reshape(1, -1, *([1] * (ins.ndim - 2))).astype(jnp.int32), axis=0
    )[0]


def multiplex(inputs, index, name=None):
    return _multiplex_raw(*inputs, index)


# ------------------------------------------------------------ reductions ----


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@op("sum")
def _sum_raw(x, axis=None, keepdim=False, out_dtype=None):
    return jnp.sum(x, axis=axis, keepdims=keepdim, dtype=out_dtype)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    out_dtype = dtypes.convert_dtype(dtype)
    if out_dtype is None and dtypes.is_integer(x.dtype) and x.dtype != jnp.int64:
        out_dtype = jnp.dtype("int64")
    return _sum_raw(x, axis=_axis(axis), keepdim=keepdim, out_dtype=out_dtype)


@op("mean")
def _mean_raw(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _mean_raw(x, axis=_axis(axis), keepdim=keepdim)


@op("max")
def _max_raw(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _max_raw(x, axis=_axis(axis), keepdim=keepdim)


@op("min")
def _min_raw(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return _min_raw(x, axis=_axis(axis), keepdim=keepdim)


@op("amax")
def _amax_raw(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amax(x, axis=None, keepdim=False, name=None):
    return _amax_raw(x, axis=_axis(axis), keepdim=keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return _min_raw(x, axis=_axis(axis), keepdim=keepdim)


@op("prod")
def _prod_raw(x, axis=None, keepdim=False):
    return jnp.prod(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    out = _prod_raw(x, axis=_axis(axis), keepdim=keepdim)
    return out.astype(dtype) if dtype is not None else out


@op("logsumexp")
def _logsumexp_raw(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp_raw(x, axis=_axis(axis), keepdim=keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return Tensor(jnp.all(x._value, axis=_axis(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return Tensor(jnp.any(x._value, axis=_axis(axis), keepdims=keepdim))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(
        jnp.count_nonzero(x._value, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64)
    )


@op("cumsum")
def _cumsum_raw(x, axis=None):
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    out = _cumsum_raw(x, axis=axis if axis is None else int(axis))
    return out.astype(dtype) if dtype is not None else out


@op("cumprod")
def _cumprod_raw(x, dim=None):
    return jnp.cumprod(x, axis=dim)


def cumprod(x, dim=None, dtype=None, name=None):
    out = _cumprod_raw(x, dim=dim)
    return out.astype(dtype) if dtype is not None else out


@op("cummax_val")
def _cummax_raw(x, axis):
    return lax.cummax(x, axis=axis)


def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape([-1])
        axis = 0
    v = _cummax_raw(x, axis=axis)
    xv = x._value
    eq = xv == v._value
    n = xv.shape[axis]
    ar = jnp.arange(n).reshape([-1 if i == (axis % xv.ndim) else 1 for i in range(xv.ndim)])
    idxv = jnp.where(eq, ar, -1)
    idxv = lax.cummax(idxv, axis=axis)
    return v, Tensor(idxv.astype(dtypes.convert_dtype(dtype)))


@op("cummin_val")
def _cummin_raw(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return lax.cummin(x, axis=axis)


def cummin(x, axis=None, dtype="int64", name=None):
    v = _cummin_raw(x, axis=axis)
    ax = 0 if axis is None else axis
    xv = x._value.reshape(-1) if axis is None else x._value
    eq = xv == v._value
    n = xv.shape[ax]
    ar = jnp.arange(n).reshape([-1 if i == (ax % xv.ndim) else 1 for i in range(xv.ndim)])
    idxv = lax.cummax(jnp.where(eq, ar, -1), axis=ax)
    return v, Tensor(idxv.astype(dtypes.convert_dtype(dtype)))


# ------------------------------------------------------------- matmul -------


@op("matmul")
def _matmul_raw(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        axes = list(range(x.ndim))
        if len(axes) >= 2:
            axes[-1], axes[-2] = axes[-2], axes[-1]
            x = jnp.transpose(x, axes)
    if transpose_y:
        axes = list(range(y.ndim))
        if len(axes) >= 2:
            axes[-1], axes[-2] = axes[-2], axes[-1]
            y = jnp.transpose(y, axes)
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul_raw(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


mm = matmul


@op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@op("inner")
def inner(x, y):
    return jnp.inner(x, y)


@op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@op("kron")
def kron(x, y):
    return jnp.kron(x, y)


@op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@op("mv")
def mv(x, vec):
    return jnp.matmul(x, vec)


@op("trace")
def _trace_raw(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _trace_raw(x, offset=offset, axis1=axis1, axis2=axis2)


@op("diagonal")
def _diagonal_raw(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal_raw(x, offset=offset, axis1=axis1, axis2=axis2)


# ------------------------------------------------------------ logic-ish -----


def _cmp(opname, fn):
    """Comparison dispatch: records in static mode, never grads (the
    reference registers compare kernels without grad ops)."""

    def api(x, y, name=None):
        y = ensure_tensor(y, like=x)
        return apply_nondiff_op(opname, fn, (x, y))

    api.op_name = opname
    return api


equal = _cmp("equal", lambda a, b: a == b)
not_equal = _cmp("not_equal", lambda a, b: a != b)
greater_than = _cmp("greater_than", lambda a, b: a > b)
greater_equal = _cmp("greater_equal", lambda a, b: a >= b)
less_than = _cmp("less_than", lambda a, b: a < b)
less_equal = _cmp("less_equal", lambda a, b: a <= b)


def equal_all(x, y, name=None):
    return apply_nondiff_op("equal_all", jnp.array_equal, (x, y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_nondiff_op(
        "allclose", jnp.allclose, (x, y),
        {"rtol": rtol, "atol": atol, "equal_nan": equal_nan})


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_nondiff_op(
        "isclose", jnp.isclose, (x, y),
        {"rtol": rtol, "atol": atol, "equal_nan": equal_nan})


# -- round-4 API-audit additions (reference python/paddle/tensor/math.py) ----

@op("cross")
def _cross_raw(x, y, axis=0):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=None, name=None):
    """Cross product along ``axis`` (default: the first dim of length 3,
    reference ``tensor/linalg.py cross``)."""
    if axis is None:
        axis = next(
            (i for i, s in enumerate(x.shape) if s == 3), None)
        if axis is None:
            raise ValueError("cross: no dimension of length 3 found")
    return _cross_raw(x, ensure_tensor(y, like=x), axis=int(axis))


@op("diff")
def _diff_raw(x, prepend=None, append=None, n=1, axis=-1):
    kw = {}
    if prepend is not None:
        kw["prepend"] = prepend
    if append is not None:
        kw["append"] = append
    return jnp.diff(x, n=n, axis=axis, **kw)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _diff_raw(x, prepend, append, n=int(n), axis=int(axis))


@op("logcumsumexp")
def _logcumsumexp_raw(x, axis=None):
    if axis is None:
        return lax.cumlogsumexp(jnp.reshape(x, (-1,)), axis=0)
    return lax.cumlogsumexp(x, axis=axis)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    out = _logcumsumexp_raw(x, axis=None if axis is None else int(axis))
    from .manipulation import cast

    return cast(out, dtype) if dtype is not None else out


@op("renorm")
def _renorm_raw(x, p=2.0, axis=0, max_norm=1.0):
    ax = axis % x.ndim
    red = tuple(i for i in range(x.ndim) if i != ax)
    norms = jnp.sum(jnp.abs(x) ** p, axis=red, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm,
                      max_norm / jnp.maximum(norms, 1e-12), 1.0)
    return x * scale


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along ``axis`` (reference
    ``tensor/math.py renorm``)."""
    return _renorm_raw(x, p=float(p), axis=int(axis), max_norm=float(max_norm))


@op("tensordot")
def _tensordot_raw(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    """Paddle axes semantics: int n = last n of x vs first n of y; a FLAT
    list applies to BOTH tensors; [a_axes] likewise; [a_axes, b_axes]
    pairs them (reference ``tensor/manipulation.py tensordot``)."""
    from ..framework.tensor import Tensor as _T

    if isinstance(axes, _T):
        axes = np.asarray(axes._value).tolist()
    if isinstance(axes, (list, tuple)):
        seq = list(axes)
        flat = True
        for a in seq:
            if isinstance(a, (list, tuple, np.ndarray, _T)):
                flat = False  # builtins any/all are shadowed by paddle ops
        if flat:
            t = tuple(int(i) for i in seq)
            axes = (t, t)
        else:
            subs = [tuple(int(i) for i in np.atleast_1d(
                a._value if isinstance(a, _T) else a)) for a in seq]
            axes = (subs[0], subs[0]) if len(subs) == 1 else (subs[0],
                                                             subs[1])
    else:
        axes = int(axes)
    return _tensordot_raw(x, ensure_tensor(y, like=x), axes=axes)


def tanh_(x, name=None):
    return x._rebind(tanh(x))


def is_complex(x):
    import jax.numpy as _jnp

    return _jnp.issubdtype(x.dtype, _jnp.complexfloating)


def is_floating_point(x):
    import jax.numpy as _jnp

    return _jnp.issubdtype(x.dtype, _jnp.floating)


def is_integer(x):
    import jax.numpy as _jnp

    return _jnp.issubdtype(x.dtype, _jnp.integer)
