"""Logical & bitwise ops (reference ``python/paddle/tensor/logic.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .dispatch import ensure_tensor


def _logic(fn):
    def api(x, y=None, out=None, name=None):
        if y is None:
            return Tensor(fn(x._value))
        y = ensure_tensor(y, like=x)
        return Tensor(fn(x._value, y._value))

    return api


logical_and = _logic(jnp.logical_and)
logical_or = _logic(jnp.logical_or)
logical_xor = _logic(jnp.logical_xor)
logical_not = _logic(jnp.logical_not)
bitwise_and = _logic(jnp.bitwise_and)
bitwise_or = _logic(jnp.bitwise_or)
bitwise_xor = _logic(jnp.bitwise_xor)
bitwise_not = _logic(jnp.bitwise_not)


def is_tensor(x):
    return isinstance(x, Tensor)
