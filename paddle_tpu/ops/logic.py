"""Logical & bitwise ops (reference ``python/paddle/tensor/logic.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .dispatch import apply_nondiff_op, ensure_tensor


def _logic(opname, fn):
    def api(x, y=None, out=None, name=None):
        if y is None:
            return apply_nondiff_op(opname, fn, (x,))
        y = ensure_tensor(y, like=x)
        return apply_nondiff_op(opname, fn, (x, y))

    api.op_name = opname
    return api


logical_and = _logic("logical_and", jnp.logical_and)
logical_or = _logic("logical_or", jnp.logical_or)
logical_xor = _logic("logical_xor", jnp.logical_xor)
logical_not = _logic("logical_not", jnp.logical_not)
bitwise_and = _logic("bitwise_and", jnp.bitwise_and)
bitwise_or = _logic("bitwise_or", jnp.bitwise_or)
bitwise_xor = _logic("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _logic("bitwise_not", jnp.bitwise_not)


def is_tensor(x):
    return isinstance(x, Tensor)
