"""Fused ops for the memory-bound tails of transformer training.

Reference analogues: ``paddle/fluid/operators/fused/fused_softmax_mask.cu.h``
and ``paddle/phi/kernels/gpu/cross_entropy_kernel.cu`` (their answer to the
softmax/CE bandwidth problem). TPU-native redesign: the LM head matmul and the
softmax cross-entropy are fused into ONE chunked op with a custom VJP, so the
full ``[tokens, vocab]`` logits tensor is never materialized in HBM — neither
in forward nor in backward. Each chunk's logits live only as a fused-scan
temporary; the MXU does the matmuls, fp32 statistics ride in registers.

For GPT-2 124M at b16xs1024 the un-fused path writes+reads a 3.3 GB fp32
logits tensor twice per step; this op removes all of that traffic.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .dispatch import op

__all__ = ["fused_linear_cross_entropy"]


# test/bench override for chunk-size sweeps (None = auto)
_FORCE_CHUNK = None
# test override: None = auto (Pallas on TPU/interpret), False = XLA scan
_FORCE_PALLAS = None


def _use_pallas(tokens, vocab, hidden):
    """Pallas flash-CE path gate.

    Measured on v5e (GPT-2 124M, b16 s1024, V=50304): fused CE is
    VPU-EXP-BOUND — ~824M f32 exps/step set a ~8-9 ms floor that neither
    implementation can dodge. The Pallas forward edges the XLA scan (14.5
    vs 15.7 ms, blocks 1024x1024) but its backward recomputes the logits
    in BOTH the dx and dW kernels, losing fwd+bwd overall (41 vs 37 ms) —
    so the scan stays the default on hardware and the kernel is opt-in
    via FLAGS_enable_flash_ce (and the default under interpret mode,
    which keeps it correctness-tested)."""
    if _FORCE_PALLAS is not None:
        return _FORCE_PALLAS
    from . import pallas
    from .pallas import fused_ce

    if not pallas.is_available() or not fused_ce.supports(hidden):
        return False
    if pallas.interpret_requested():
        return True
    from ..framework.flags import flag_value

    return bool(flag_value("enable_flash_ce"))


def _pick_chunk(tokens: int) -> int:
    # largest power-of-two chunk <= 2048 dividing the padded token count.
    # Swept on v5e (GPT-2 124M, V=50304, 16k tokens): ISOLATED fwd+bwd
    # prefers 4096/8192 (35.7/35.4 ms vs 39.2 at 2048 — fewer dW-carry
    # trips), but END-TO-END the larger transient logits block loses
    # ~4.5k tok/s to HBM pressure against the resident model state —
    # 2048 (~400 MB transient) is the full-step optimum.
    if _FORCE_CHUNK:
        return min(_FORCE_CHUNK, tokens)
    for c in (2048, 1024, 512, 256, 128):
        if tokens >= c:
            return c
    return tokens


def _chunked(x, chunk):
    n = x.shape[0]
    pad = (-n) % chunk
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], 0)
    return x.reshape((x.shape[0] // chunk, chunk) + x.shape[1:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flce(h, w, b, labels, ignore_index, chunk):
    losses, _ = _flce_fwd(h, w, b, labels, ignore_index, chunk)
    return losses


def _flce_fwd(h, w, b, labels, ignore_index, chunk):
    tokens = h.shape[0]
    chunk = chunk or _pick_chunk(tokens)
    y = labels.astype(jnp.int32)
    safe = jnp.where(y == ignore_index, 0, y)
    vocab = w.shape[0]

    if _use_pallas(tokens, vocab, h.shape[-1]):
        from .pallas import fused_ce, interpret_requested

        losses, lse = fused_ce.ce_forward(
            h, w, None if b.ndim == 0 else b, safe,
            interpret=interpret_requested())
        losses = jnp.where(y == ignore_index, 0.0, losses)
        return losses, (h, w, b, safe, y == ignore_index, lse)

    h_b = _chunked(h, chunk)

    def body(_, h_c):
        logits = jnp.dot(h_c, w.T, preferred_element_type=jnp.float32) + b  # [C,V]
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        return None, lse

    _, lse_b = lax.scan(body, None, h_b)
    # the label logit never needs the [C, V] block: it is a row gather of W
    # plus a row-dot — h_i . W[y_i] + b[y_i]. Computing it in the scan as a
    # one-hot select+reduce re-read the full f32 logits chunk (~400 MB x
    # nchunks of pure HBM traffic, profiled at ~4.4 ms/step on v5e).
    picked = jnp.sum(
        h.astype(jnp.float32) * jnp.take(w, safe, axis=0).astype(jnp.float32),
        axis=-1,
    )
    if b.ndim != 0:
        picked = picked + jnp.take(b, safe).astype(jnp.float32)
    losses = lse_b.reshape(-1)[:tokens] - picked
    losses = jnp.where(y == ignore_index, 0.0, losses)
    return losses, (h, w, b, safe, y == ignore_index, lse_b)


def _flce_bwd(ignore_index, chunk, res, g):
    h, w, b, safe, ignored, lse_b = res
    tokens = h.shape[0]
    chunk = chunk or _pick_chunk(tokens)
    g = jnp.where(ignored, 0.0, g.astype(jnp.float32))

    # branch on the residual itself: the Pallas forward saves a flat
    # (tokens,) lse, the scan forward a chunked 2-D one — intrinsic to the
    # residuals, immune to any gate flip between fwd and bwd tracing
    if lse_b.ndim == 1:
        from .pallas import fused_ce, interpret_requested

        dh, dw, db = fused_ce.ce_backward(
            h, w, None if b.ndim == 0 else b, safe, g, lse_b,
            interpret=interpret_requested())
        db_out = (jnp.zeros((), jnp.float32) if b.ndim == 0
                  else db.astype(b.dtype))
        return dh, dw.astype(w.dtype), db_out, None

    h_b = _chunked(h, chunk)
    y_b = _chunked(safe, chunk)
    g_b = _chunked(g, chunk)

    def body(acc, inp):
        dw_acc, db_acc = acc
        h_c, y_c, g_c, lse_c = inp
        logits = jnp.dot(h_c, w.T, preferred_element_type=jnp.float32) + b
        # softmax from the saved forward lse: single fused pass, no max/sum
        # re-reduction; one-hot via iota compare keeps this scatter-free
        eq = (lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == y_c[:, None]).astype(jnp.float32)
        dl = ((jnp.exp(logits - lse_c[:, None]) - eq)
              * g_c[:, None]).astype(w.dtype)              # [C, V] bf16
        dh_c = jnp.dot(dl, w)                              # [C, H]
        dw_acc = dw_acc + jnp.dot(dl.T, h_c, preferred_element_type=jnp.float32)
        if b.ndim == 0:
            # bias-free path: the placeholder's cotangent is never consumed —
            # skip the O(chunk*vocab) reduction entirely
            pass
        else:
            db_acc = db_acc + jnp.sum(dl.astype(jnp.float32), axis=0)
        return (dw_acc, db_acc), dh_c

    dw0 = jnp.zeros(w.shape, jnp.float32)
    db0 = jnp.zeros(b.shape, jnp.float32)
    (dw, db), dh_b = lax.scan(body, (dw0, db0), (h_b, y_b, g_b, lse_b))
    dh = dh_b.reshape(-1, h.shape[-1])[:tokens].astype(h.dtype)
    return dh, dw.astype(w.dtype), db.astype(b.dtype), None


_flce.defvjp(_flce_fwd, _flce_bwd)


@op("fused_linear_cross_entropy")
def _flce_op(hidden, weight, labels, bias=None, ignore_index=-100,
             reduction="mean", chunk=0):
    tokens = 1
    for d in hidden.shape[:-1]:
        tokens *= d
    h2 = hidden.reshape(tokens, hidden.shape[-1])
    y = labels.reshape(tokens)
    # bias-free callers pay nothing: a scalar 0 broadcasts into the chunk
    # logits and its (discarded) gradient is one extra scalar reduction
    b = jnp.zeros((), jnp.float32) if bias is None else bias.astype(jnp.float32)
    losses = _flce(h2, weight, b, y, ignore_index, chunk)
    if reduction == "none":
        return losses.reshape(labels.shape)
    valid = jnp.sum((y != ignore_index).astype(jnp.float32))
    total = jnp.sum(losses)
    if reduction == "sum":
        return total
    return total / jnp.maximum(valid, 1.0)


def fused_linear_cross_entropy(hidden, weight, labels, bias=None,
                               ignore_index=-100, reduction="mean", chunk=0,
                               name=None):
    """``cross_entropy(hidden @ weight.T + bias, labels)`` without
    materializing logits.

    Args:
        hidden: ``[..., hidden_size]`` activations (bf16/f32).
        weight: ``[vocab, hidden_size]`` LM head / tied embedding weight.
        labels: integer class ids, shape ``hidden.shape[:-1]``.
        bias: optional ``[vocab]`` LM-head bias (ERNIE/BERT-style heads).
        ignore_index: label value excluded from the loss and the mean.
        reduction: ``"mean" | "sum" | "none"``.
        chunk: token-chunk size (0 = auto).
    """
    if bias is None:
        return _flce_op(hidden, weight, labels, ignore_index=ignore_index,
                        reduction=reduction, chunk=int(chunk))
    return _flce_op(hidden, weight, labels, bias,
                    ignore_index=ignore_index,
                    reduction=reduction, chunk=int(chunk))
