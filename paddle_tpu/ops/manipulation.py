"""Shape / layout / indexing ops (reference ``python/paddle/tensor/manipulation.py``)."""
from __future__ import annotations

import builtins

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor
from .dispatch import op, ensure_tensor


def _as_dim(s):
    """A SHAPE entry: concrete int, or a symbolic export dimension (jax
    shape polymorphism — dynamic-batch jit.save), which must pass through
    unforced. Only shape-taking ops (reshape/expand/tile) accept symbolic
    entries; axis/shift/slice arguments stay strictly int (_ints) so bad
    values still fail loudly at the API boundary."""
    if isinstance(s, Tensor):
        s = s._value
    if isinstance(s, (int, np.integer)):
        return int(s)
    from jax.export import is_symbolic_dim

    if is_symbolic_dim(s):
        return s
    return int(s)


def _dims(shape):
    """Shape parser: ints + symbolic export dims."""
    if isinstance(shape, Tensor):
        return [int(v) for v in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [_as_dim(s) for s in shape]


def _ints(shape):
    if isinstance(shape, Tensor):
        return [int(v) for v in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


@op("cast")
def _cast_raw(x, to_dtype=None):
    return x.astype(to_dtype)


def cast(x, dtype):
    d = dtypes.convert_dtype(dtype)
    if x.dtype == d:
        return x
    # int->int casts etc keep stop_gradient; float casts are differentiable
    return _cast_raw(x, to_dtype=d)


@op("reshape")
def _reshape_raw(x, shape=None):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    return _reshape_raw(x, shape=tuple(_dims(shape)))


def reshape_(x, shape, name=None):
    return x._rebind(reshape(x, shape))


@op("transpose")
def _transpose_raw(x, perm=None):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose_raw(x, perm=tuple(int(p) for p in perm))


def t(x, name=None):
    if x.ndim < 2:
        return x
    return transpose(x, [1, 0])


@op("moveaxis")
def _moveaxis_raw(x, source=None, destination=None):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination, name=None):
    as_tup = lambda v: tuple(int(i) for i in np.atleast_1d(v))
    return _moveaxis_raw(x, source=as_tup(source), destination=as_tup(destination))


@op("flatten")
def _flatten_raw(x, start_axis=0, stop_axis=-1):
    shape = list(x.shape)
    nd = len(shape)
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    new_shape = shape[:s] + [-1] + shape[e + 1:]
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten_raw(x, start_axis=start_axis, stop_axis=stop_axis)


@op("squeeze")
def _squeeze_raw(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = tuple(a % x.ndim for a in axis if x.shape[a % x.ndim] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def squeeze(x, axis=None, name=None):
    if axis is not None and not isinstance(axis, (list, tuple)):
        axis = [int(axis)]
    return _squeeze_raw(x, axis=tuple(axis) if axis is not None else None)


def squeeze_(x, axis=None, name=None):
    return x._rebind(squeeze(x, axis))


@op("unsqueeze")
def _unsqueeze_raw(x, axis=()):
    for a in axis:
        x = jnp.expand_dims(x, a)
    return x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = _ints(axis)
    if not isinstance(axis, (list, tuple)):
        axis = [int(axis)]
    return _unsqueeze_raw(x, axis=tuple(int(a) for a in axis))


def unsqueeze_(x, axis, name=None):
    return x._rebind(unsqueeze(x, axis))


@op("concat")
def _concat_raw(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _concat_raw(*x, axis=int(axis))


@op("stack")
def _stack_raw(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack_raw(*x, axis=int(axis))


@op("unstack_op")
def _unstack_raw(x, axis=0, num=None):
    n = num or x.shape[axis]
    return tuple(jnp.squeeze(s, axis) for s in jnp.split(x, n, axis=axis))


def unstack(x, axis=0, num=None):
    out = _unstack_raw(x, axis=axis, num=num)
    return list(out)


@op("split_op")
def _split_raw(x, indices=None, axis=0):
    return tuple(jnp.split(x, indices, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    if isinstance(num_or_sections, int):
        out = _split_raw(x, indices=num_or_sections, axis=axis)
    else:
        secs = _ints(num_or_sections)
        total = x.shape[axis]
        known = sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        out = _split_raw(x, indices=idx, axis=axis)
    return list(out)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def vsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=1 if x.ndim > 1 else 0)


def dsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=2)


@op("tile")
def _tile_raw(x, repeat_times=()):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    return _tile_raw(x, repeat_times=tuple(_dims(repeat_times)))


@op("expand")
def _expand_raw(x, shape=()):
    shape = list(shape)
    # -1 means keep dim
    nd_new = len(shape)
    xshape = list(x.shape)
    aligned = [1] * (nd_new - len(xshape)) + xshape
    out_shape = [aligned[i] if shape[i] == -1 else shape[i] for i in range(nd_new)]
    return jnp.broadcast_to(jnp.reshape(x, aligned), out_shape)


def expand(x, shape, name=None):
    return _expand_raw(x, shape=tuple(_dims(shape)))


def expand_as(x, y, name=None):
    return _expand_raw(x, shape=tuple(y.shape))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(t, out_shape) for t in inputs]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@op("flip")
def _flip_raw(x, axis=()):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    if not isinstance(axis, (list, tuple)):
        axis = [axis]
    return _flip_raw(x, axis=tuple(int(a) for a in axis))


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90_raw(x, k=k, axes=tuple(axes))


@op("rot90")
def _rot90_raw(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


@op("roll")
def _roll_raw(x, shifts=None, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, Tensor):
        shifts = _ints(shifts)
        shifts = shifts[0] if len(shifts) == 1 else tuple(shifts)
    elif isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    return _roll_raw(x, shifts=shifts, axis=axis)


# ------------------------------------------------------------- indexing -----


@op("getitem")
def _getitem_raw(x, *index_tensors, idx_spec=None):
    # rebuild index tuple with tensor indices substituted back in
    it = iter(index_tensors)
    idx = tuple(next(it) if s is _TENSOR_SLOT else s for s in idx_spec)
    return x[idx]


class _Slot:
    pass


_TENSOR_SLOT = _Slot()


def _normalize_index(idx):
    """Split an index expression into (spec with slots, tensor args)."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    spec = []
    tensors = []
    for it in idx:
        if isinstance(it, Tensor):
            v = it._value
            if v.dtype == jnp.bool_:
                # boolean mask -> nonzero indices would be dynamic; keep as array
                tensors.append(v)
                spec.append(_TENSOR_SLOT)
            else:
                tensors.append(v.astype(jnp.int32) if v.dtype == jnp.int64 else v)
                spec.append(_TENSOR_SLOT)
        elif isinstance(it, np.ndarray):
            tensors.append(jnp.asarray(it))
            spec.append(_TENSOR_SLOT)
        elif isinstance(it, (list,)) and it and not isinstance(it[0], (slice, type(None))):
            tensors.append(jnp.asarray(it))
            spec.append(_TENSOR_SLOT)
        else:
            spec.append(it)
    return tuple(spec), tensors


def _getitem(x, idx):
    spec, tensors = _normalize_index(idx)
    return _getitem_raw(x, *tensors, idx_spec=spec)


@op("setitem")
def _setitem_raw(x, v, *index_tensors, idx_spec=None):
    it = iter(index_tensors)
    idx = tuple(next(it) if s is _TENSOR_SLOT else s for s in idx_spec)
    if hasattr(v, "astype"):
        v = v.astype(x.dtype)
        tgt_shape = tuple(jnp.shape(x[idx]))
        if tuple(v.shape) != tgt_shape:
            # paddle allows assigning e.g. shape-(1,) values into scalar slots:
            # strip leading length-1 dims beyond the target rank, then broadcast
            while v.ndim > len(tgt_shape) and v.shape[0] == 1:
                v = v.reshape(v.shape[1:])
            v = jnp.broadcast_to(v, tgt_shape)
    return x.at[idx].set(v)


def _setitem_(x, idx, value):
    """__setitem__: functional scatter + in-place rebind (autograd-correct)."""
    spec, tensors = _normalize_index(idx)
    value = value if isinstance(value, Tensor) else Tensor(jnp.asarray(value, x.dtype))
    out = _setitem_raw(x, value, *tensors, idx_spec=spec)
    x._rebind(out)
    return x


@op("slice_op")
def _slice_raw(x, axes=(), starts=(), ends=()):
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(s, e)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):  # noqa: A001
    return _slice_raw(x, axes=tuple(_ints(axes)), starts=tuple(_ints(starts)), ends=tuple(_ints(ends)))


builtins_slice = builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    idx = [builtins_slice(None)] * x.ndim
    for a, s, e, st in zip(_ints(axes), _ints(starts), _ints(ends), _ints(strides)):
        idx[a] = builtins_slice(s, e, st)
    return _getitem(x, tuple(idx))


@op("gather")
def _gather_raw(x, index, axis=0):
    if index.ndim > 1:
        index = index.reshape(-1)
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    index = ensure_tensor(index)
    # index flattening happens inside the op (symbolic-Variable safe)
    return _gather_raw(x, index, axis=int(axis))


@op("gather_nd")
def _gather_nd_raw(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return _gather_nd_raw(x, ensure_tensor(index))


@op("take_along_axis")
def _take_along_axis_raw(x, indices, axis=0, broadcast=True):
    if broadcast:
        # paddle broadcasts indices against arr except on `axis`
        tgt = list(x.shape)
        tgt[axis] = (indices.shape[axis] if indices.ndim == x.ndim
                     else indices.shape[-1])
        indices = jnp.broadcast_to(indices, tgt)
    return jnp.take_along_axis(x, indices, axis=axis)


def take_along_axis(arr, indices, axis, broadcast=True):
    return _take_along_axis_raw(arr, indices, axis=axis, broadcast=broadcast)


@op("put_along_axis")
def _put_along_axis_raw(x, indices, values, axis=0, reduce="assign",
                        include_self=True, bshape=None):
    if bshape is not None:
        # index + values broadcasts happen INSIDE the recorded op so the
        # caller's values tensor keeps its autograd link and static
        # Variables stay symbolic (host-side broadcast_to on a fresh Tensor
        # dropped the gradient; .reshape on a ShapeDtypeStruct raised)
        if indices.ndim != x.ndim:
            indices = indices.reshape(
                [-1 if i == axis else 1 for i in range(x.ndim)])
        indices = jnp.broadcast_to(indices, bshape)
        values = (jnp.broadcast_to(values, bshape) if getattr(values, "ndim", 0)
                  else jnp.full(bshape, values, x.dtype))
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    dn = jnp.zeros_like(x) if not include_self else x
    if reduce in ("add", "sum"):
        base = x if include_self else jnp.put_along_axis(x, indices, 0, axis=axis, inplace=False)
        upd = jnp.zeros_like(x)
        upd = _scatter_add_along(upd, indices, values, axis)
        return base + upd
    raise NotImplementedError(f"put_along_axis reduce={reduce}")


def _scatter_add_along(zeros, indices, values, axis):
    # build full index grid and scatter-add
    idx_full = jnp.indices(indices.shape)
    idx = list(idx_full)
    idx[axis] = indices
    return zeros.at[tuple(idx)].add(values)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True):
    values = ensure_tensor(values, like=arr)
    bshape = None
    if broadcast:
        tgt = list(arr.shape)
        idx_ndim = len(indices.shape)
        tgt[axis] = indices.shape[axis] if idx_ndim == len(arr.shape) else 1
        bshape = tuple(tgt)
    return _put_along_axis_raw(arr, indices, values, axis=axis,
                               reduce=reduce, include_self=include_self,
                               bshape=bshape)


@op("scatter")
def _scatter_raw(x, index, updates, overwrite=True):
    if index.ndim == 2:
        index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    base = x.at[index].set(jnp.zeros_like(updates))
    return base.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter_raw(x, ensure_tensor(index), updates, overwrite=overwrite)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._rebind(scatter(x, index, updates, overwrite))


@op("scatter_nd_add")
def _scatter_nd_add_raw(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add_raw(x, ensure_tensor(index), updates)


def scatter_nd(index, updates, shape, name=None):
    from . import creation

    zeros = creation.zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(zeros, index, updates)


@op("index_select")
def _index_select_raw(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select_raw(x, ensure_tensor(index), axis=axis)


@op("index_sample")
def _index_sample_raw(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index):
    return _index_sample_raw(x, ensure_tensor(index))


@op("index_add")
def _index_add_raw(x, index, value, axis=0):
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, axis)


def index_add(x, index, axis, value, name=None):
    return _index_add_raw(x, ensure_tensor(index), value, axis=axis)


def index_put(x, indices, value, accumulate=False, name=None):
    spec, tensors = _normalize_index(tuple(indices))
    value = ensure_tensor(value, like=x)
    if accumulate:
        return _index_put_add_raw(x, value, *tensors, idx_spec=spec)
    return _setitem_raw(x, value, *tensors, idx_spec=spec)


@op("index_put_add")
def _index_put_add_raw(x, v, *index_tensors, idx_spec=None):
    it = iter(index_tensors)
    idx = tuple(next(it) if s is _TENSOR_SLOT else s for s in idx_spec)
    return x.at[idx].add(v)


@op("masked_select_sized")
def _masked_select_raw(x, mask, size=None):
    # XLA needs static size; paddle's masked_select is dynamic -> we
    # materialize via nonzero with a static total size (the full numel).
    flat_x = x.reshape(-1)
    flat_m = mask.reshape(-1)
    idx = jnp.nonzero(flat_m, size=size, fill_value=0)[0]
    return jnp.take(flat_x, idx)


def masked_select(x, mask, name=None):
    mask_b = jnp.broadcast_to(mask._value, x._value.shape)
    n = int(jnp.sum(mask_b))  # dynamic: forces sync in eager, documented
    return _masked_select_raw(x, Tensor(mask_b), size=n)


@op("masked_fill")
def _masked_fill_raw(x, mask, value):
    return jnp.where(mask, value, x)


def masked_fill(x, mask, value, name=None):
    value = ensure_tensor(value, like=x)
    return _masked_fill_raw(x, ensure_tensor(mask), value)


def masked_fill_(x, mask, value, name=None):
    return x._rebind(masked_fill(x, mask, value))


@op("fill_diagonal")
def _fill_diagonal_raw(x, value=0.0, offset=0, wrap=False):
    n = min(x.shape[0], x.shape[1])
    i = jnp.arange(n - abs(offset))
    r = i if offset >= 0 else i - offset
    c = i + offset if offset >= 0 else i
    return x.at[r, c].set(value)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    return x._rebind(_fill_diagonal_raw(x, value=value, offset=offset, wrap=wrap))


@op("where")
def _where_raw(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x = ensure_tensor(x, like=y if isinstance(y, Tensor) else None)
    y = ensure_tensor(y, like=x)
    return _where_raw(condition, x, y)


def nonzero(x, as_tuple=False):
    # dynamic-shaped: eager-only (forces host sync), like reference nonzero
    idx = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, jnp.int64)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1), jnp.int64))


# ---------------------------------------------------------------- pad -------


@op("pad_nd")
def _pad_raw(x, pad=(), mode="constant", value=0.0):
    return jnp.pad(x, pad, mode=mode, **({"constant_values": value} if mode == "constant" else {}))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A001
    pad = _ints(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        # paddle full-rank form: [d0_l, d0_r, d1_l, d1_r, ...]
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial form applies to spatial dims per data_format (reference
        # nn/functional/common.py pad): reversed pairs on trailing dims
        k = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC/NDHWC/NLC
            spatial = list(range(1, 1 + k))
        else:
            spatial = list(range(nd - k, nd))
        for j, d in enumerate(spatial):
            width[d] = (pad[2 * j], pad[2 * j + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return _pad_raw(x, pad=tuple(width), mode=jmode, value=value)


# ------------------------------------------------------------- misc ---------


@op("repeat_interleave")
def _repeat_interleave_raw(x, repeats=None, axis=None, index=None, total=None):
    if index is not None:
        return jnp.repeat(x, index, axis=axis, total_repeat_length=total)
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        total = int(np.asarray(repeats._value).sum())
        return _repeat_interleave_raw(x, axis=axis, index=repeats._value, total=total)
    return _repeat_interleave_raw(x, repeats=int(repeats), axis=axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    a = np.asarray(x._value)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    a = np.asarray(x._value)
    if axis is None:
        a = a.reshape(-1)
    keep = np.ones(a.shape[0 if axis is None else axis], bool)
    comp = a if axis is None else np.moveaxis(a, axis, 0)
    keep[1:] = [not np.array_equal(comp[i], comp[i - 1]) for i in range(1, comp.shape[0])]
    vals = comp[keep]
    outs = [Tensor(jnp.asarray(vals if axis is None else np.moveaxis(vals, 0, axis)))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv, np.int64)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        cnt = np.diff(np.append(idx, comp.shape[0]))
        outs.append(Tensor(jnp.asarray(cnt, np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


@op("as_real")
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op("as_complex")
def as_complex(x):
    return lax.complex(x[..., 0], x[..., 1])


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def shape(x):
    return Tensor(jnp.asarray(x.shape, jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, jnp.int32))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def increment(x, value=1.0, name=None):
    return x._rebind(_increment_raw(x, value=value))


@op("increment")
def _increment_raw(x, value=1.0):
    return x + value


def tolist(x):
    return x.tolist()


def crop(x, shape=None, offsets=None, name=None):
    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else [0] * x.ndim
    idx = tuple(builtins_slice(o, o + s if s != -1 else None) for o, s in zip(offsets, shape))
    return _getitem(x, idx)


# -- round-4 API-audit additions (reference tensor/manipulation.py) ----------

def reverse(x, axis, name=None):
    """Reference ``fluid.layers.reverse`` — alias of flip."""
    return flip(x, axis)


def unbind(input, axis=0, name=None):
    """Split along ``axis`` into a list with that dim removed (reference
    ``tensor/manipulation.py unbind``)."""
    return unstack(input, axis=int(axis))


@op("shard_index")
def _shard_index_raw(x, index_num=0, nshards=1, shard_id=0, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    in_shard = (x >= lo) & (x < lo + shard_size)
    return jnp.where(in_shard, x - lo, ignore_value)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Map global indices to shard-local offsets, ``ignore_value`` outside
    this shard (reference ``tensor/manipulation.py:485`` — the distributed
    embedding / sharded-softmax label remap)."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"shard_id({shard_id}) should be in [0, {nshards})")
    return _shard_index_raw(input, index_num=int(index_num),
                            nshards=int(nshards), shard_id=int(shard_id),
                            ignore_value=int(ignore_value))
