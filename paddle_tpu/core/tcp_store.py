"""TCPStore python surface over the native store (reference
``core.TCPStore`` bound in ``pybind/distributed_py.cc``; used by
``distributed/parallel.py:240-245`` for rendezvous).

API parity: ``TCPStore(host, port, is_master, world_size, timeout)`` with
``set/get/add/wait``; plus ``barrier`` (the reference builds barriers from
add+wait in python — here it's one call).
"""
from __future__ import annotations

import ctypes
import os


class TCPStoreError(RuntimeError):
    pass


class TCPStore:
    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=900):
        from . import load_native, native_load_error

        lib = load_native()
        if lib is None:
            raise TCPStoreError(
                f"native core library unavailable: {native_load_error()!r}")
        self._lib = lib
        self._server = None
        self._client = None
        self.world_size = int(world_size)
        self.timeout_ms = int(timeout * 1000)
        if is_master:
            self._server = lib.pt_tcpstore_server_start(int(port))
            if not self._server:
                raise TCPStoreError(f"cannot bind TCPStore server on port {port}")
            port = lib.pt_tcpstore_server_port(self._server)
        self.host = host
        self.port = int(port)
        self._client = lib.pt_tcpstore_connect(
            host.encode(), self.port, self.timeout_ms)
        if not self._client:
            self.close()
            raise TCPStoreError(
                f"cannot connect to TCPStore at {host}:{self.port}")

    # -- KV API -------------------------------------------------------------

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        rc = self._lib.pt_tcpstore_set(
            self._client, key.encode(), bytes(value), len(value))
        if rc != 0:
            raise TCPStoreError(f"set({key!r}) failed")

    def get(self, key, timeout=None):
        to = self.timeout_ms if timeout is None else int(timeout * 1000)
        buflen = 1 << 16
        for _ in range(2):
            buf = ctypes.create_string_buffer(buflen)
            rc = self._lib.pt_tcpstore_get(
                self._client, key.encode(), buf, buflen, to)
            if rc >= 0:
                return buf.raw[:rc]
            if rc == -1:
                raise TCPStoreError(f"get({key!r}): timeout after {to} ms")
            if rc <= -3:
                buflen = -rc - 3 + 16
                continue
            raise TCPStoreError(f"get({key!r}): connection error")
        raise TCPStoreError(f"get({key!r}): value too large")

    def add(self, key, amount=1):
        st = ctypes.c_int(0)
        out = self._lib.pt_tcpstore_add(
            self._client, key.encode(), int(amount), ctypes.byref(st))
        if st.value != 0:
            raise TCPStoreError(f"add({key!r}) failed")
        return int(out)

    def wait(self, keys, timeout=None):
        to = self.timeout_ms if timeout is None else int(timeout * 1000)
        if isinstance(keys, (str, bytes)):
            keys = [keys]
        for k in keys:
            k = k.decode() if isinstance(k, bytes) else k
            rc = self._lib.pt_tcpstore_wait(self._client, k.encode(), to)
            if rc == -1:
                raise TCPStoreError(f"wait({k!r}): timeout after {to} ms")
            if rc != 0:
                raise TCPStoreError(f"wait({k!r}): connection error")

    def barrier(self, name="barrier", world_size=None, timeout=None):
        """All ranks arrive (add) then wait for the release key the last
        rank publishes."""
        n = int(world_size or self.world_size)
        arrived = self.add(f"__barrier/{name}/count", 1)
        if arrived % n == 0:
            self.set(f"__barrier/{name}/release{arrived // n}", b"1")
        gen = (arrived + n - 1) // n
        self.wait([f"__barrier/{name}/release{gen}"], timeout)

    def close(self):
        if self._client:
            self._lib.pt_tcpstore_close(self._client)
            self._client = None
        if self._server:
            self._lib.pt_tcpstore_server_stop(self._server)
            self._server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
