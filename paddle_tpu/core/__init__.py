"""paddle_tpu.core — native (C++) runtime components.

The reference keeps its runtime in C++ (pybind module ``core_avx``,
``pybind/pybind.cc:558``); here the XLA runtime owns kernels/streams/memory,
and this package holds the host-side native pieces that remain OUR runtime's
job rather than the compiler's:

- ``tcp_store.cc`` — rendezvous/barrier KV store
  (reference ``distributed/store/tcp_store.cc``);
- ``host_tracer.cc`` — nanosecond RecordEvent sink for the profiler
  (reference ``platform/profiler/host_tracer.cc``).

Sources live in ``native/`` and are compiled on demand with g++ into a
shared library loaded via ctypes (no pybind11 in this environment — the
C-ABI + ctypes route is the binding layer, reference L5).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_HERE, "native")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libpaddle_tpu_core.so")

_SOURCES = ("tcp_store.cc", "host_tracer.cc")

_lock = threading.Lock()
_lib = None
_load_error = None


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime for s in _SOURCES
    )


def _build():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    tmp = _LIB_PATH + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", tmp, *srcs,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, _LIB_PATH)  # atomic wrt concurrent builders


def _declare(lib):
    c = ctypes
    lib.pt_tcpstore_server_start.restype = c.c_void_p
    lib.pt_tcpstore_server_start.argtypes = [c.c_int]
    lib.pt_tcpstore_server_port.restype = c.c_int
    lib.pt_tcpstore_server_port.argtypes = [c.c_void_p]
    lib.pt_tcpstore_server_stop.argtypes = [c.c_void_p]
    lib.pt_tcpstore_connect.restype = c.c_void_p
    lib.pt_tcpstore_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_tcpstore_close.argtypes = [c.c_void_p]
    lib.pt_tcpstore_set.restype = c.c_int
    lib.pt_tcpstore_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]
    lib.pt_tcpstore_get.restype = c.c_int
    lib.pt_tcpstore_get.argtypes = [
        c.c_void_p, c.c_char_p, c.c_char_p, c.c_int, c.c_int]
    lib.pt_tcpstore_add.restype = c.c_longlong
    lib.pt_tcpstore_add.argtypes = [
        c.c_void_p, c.c_char_p, c.c_longlong, c.POINTER(c.c_int)]
    lib.pt_tcpstore_wait.restype = c.c_int
    lib.pt_tcpstore_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pt_tracer_start.restype = c.c_int
    lib.pt_tracer_start.argtypes = [c.c_longlong]
    lib.pt_tracer_record.restype = c.c_int
    lib.pt_tracer_record.argtypes = [c.c_char_p, c.c_longlong, c.c_longlong]
    lib.pt_tracer_now_ns.restype = c.c_longlong
    lib.pt_tracer_count.restype = c.c_longlong
    lib.pt_tracer_dump.restype = c.c_longlong
    lib.pt_tracer_dump.argtypes = [c.c_char_p, c.c_longlong]
    return lib


def load_native():
    """Build (if needed) and load the native library. Returns None and
    remembers the error when the toolchain is unavailable — callers fall
    back to pure-python paths."""
    global _lib, _load_error
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        try:
            if _stale():
                _build()
            _lib = _declare(ctypes.CDLL(_LIB_PATH))
        except Exception as e:  # noqa: BLE001 - record & degrade
            _load_error = e
            _lib = None
        return _lib


def native_load_error():
    return _load_error


from .tcp_store import TCPStore  # noqa: E402,F401
