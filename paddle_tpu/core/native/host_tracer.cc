// Host-side event tracer: the native RecordEvent sink.
//
// Reference analogue: paddle/fluid/platform/profiler/host_tracer.cc +
// chrometracing_logger.cc — RecordEvent annotations throughout the host hot
// paths append to a per-thread buffer with nanosecond clocks, later merged
// and exported as chrome trace.
//
// TPU-native role: python-side RecordEvent (paddle_tpu/profiler) calls here
// via ctypes so the common record path costs a clock read + an append into a
// preallocated slab instead of python object churn; the device timeline
// comes from jax's XPlane profiler and the two are merged at export.
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Event {
  std::string name;
  int64_t start_ns;
  int64_t end_ns;
  uint64_t tid;
};

std::mutex g_mu;
std::vector<Event> g_events;
bool g_enabled = false;
size_t g_capacity = 0;

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

int pt_tracer_start(long long capacity) {
  std::lock_guard<std::mutex> g(g_mu);
  g_events.clear();
  g_capacity = static_cast<size_t>(capacity);
  g_events.reserve(g_capacity);
  g_enabled = true;
  return 0;
}

void pt_tracer_stop() {
  std::lock_guard<std::mutex> g(g_mu);
  g_enabled = false;
}

long long pt_tracer_now_ns() { return now_ns(); }

int pt_tracer_record(const char* name, long long start_ns, long long end_ns) {
  std::lock_guard<std::mutex> g(g_mu);
  if (!g_enabled || g_events.size() >= g_capacity) return -1;
  uint64_t tid = std::hash<std::thread::id>{}(std::this_thread::get_id());
  g_events.push_back(Event{name, start_ns, end_ns, tid});
  return 0;
}

long long pt_tracer_count() {
  std::lock_guard<std::mutex> g(g_mu);
  return static_cast<long long>(g_events.size());
}

// Serialize all events as lines "name\tstart\tend\ttid\n" into buf.
// Returns bytes written, or -needed when buflen is too small.
long long pt_tracer_dump(char* buf, long long buflen) {
  std::lock_guard<std::mutex> g(g_mu);
  std::string out;
  for (const auto& e : g_events) {
    out += e.name;
    out += '\t';
    out += std::to_string(e.start_ns);
    out += '\t';
    out += std::to_string(e.end_ns);
    out += '\t';
    out += std::to_string(e.tid);
    out += '\n';
  }
  if (static_cast<long long>(out.size()) > buflen)
    return -static_cast<long long>(out.size());
  std::memcpy(buf, out.data(), out.size());
  return static_cast<long long>(out.size());
}

void pt_tracer_clear() {
  std::lock_guard<std::mutex> g(g_mu);
  g_events.clear();
}

}  // extern "C"
