// TCPStore: socket KV store for multi-process rendezvous and barriers.
//
// Reference analogue: paddle/fluid/distributed/store/tcp_store.cc +
// tcp_utils.cc (the bootstrap KV store behind init_parallel_env; rank 0
// hosts, every rank connects, keys carry endpoint/uniqueid payloads and
// atomic counters implement barriers).
//
// TPU-native role: jax's distributed runtime brings its own coordination
// service for device initialization, but the framework still needs a
// general-purpose host-side store for the launch CLI (electing the
// coordinator, publishing per-rank endpoints, exit barriers) and for
// user-level Store APIs. This is a from-scratch implementation: a
// single-threaded-per-connection blocking server over a mutex-protected
// map with a condition variable for waiters.
//
// Wire protocol (little-endian):
//   request : op(u8) keylen(u32) key [payload]
//     SET  (1): payload = vallen(u32) value            -> status(u8)
//     GET  (2): payload = timeout_ms(i32)              -> status(u8) [vallen(u32) value]
//     ADD  (3): payload = delta(i64)                   -> status(u8) newval(i64)
//     WAIT (4): payload = timeout_ms(i32)              -> status(u8)
//     PING (5): payload = none                         -> status(u8)
//   status: 0 = ok, 1 = timeout
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kPing = 5 };

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    if (port_ == 0) {
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
      port_ = ntohs(addr.sin_port);
    }
    if (::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return false;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    stopping_.store(true);
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> workers;
    {
      std::lock_guard<std::mutex> g(workers_mu_);
      workers.swap(workers_);
      for (int fd : client_fds_) ::shutdown(fd, SHUT_RDWR);
      client_fds_.clear();
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  int port() const { return port_; }

  ~StoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_.load()) break;
        continue;
      }
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(workers_mu_);
      client_fds_.push_back(fd);
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stopping_.load()) {
      uint8_t op;
      if (!read_full(fd, &op, 1)) break;
      uint32_t klen;
      if (!read_full(fd, &klen, 4) || klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (!read_full(fd, key.data(), klen)) break;

      if (op == kSet) {
        uint32_t vlen;
        if (!read_full(fd, &vlen, 4) || vlen > (1u << 28)) break;
        std::string val(vlen, '\0');
        if (!read_full(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> g(mu_);
          data_[key] = std::move(val);
        }
        cv_.notify_all();
        uint8_t st = 0;
        if (!write_full(fd, &st, 1)) break;
      } else if (op == kGet || op == kWait) {
        int32_t timeout_ms;
        if (!read_full(fd, &timeout_ms, 4)) break;
        std::unique_lock<std::mutex> lk(mu_);
        bool ok = cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
          return stopping_.load() || data_.count(key) > 0;
        });
        ok = ok && data_.count(key) > 0;
        if (op == kWait) {
          lk.unlock();
          uint8_t st = ok ? 0 : 1;
          if (!write_full(fd, &st, 1)) break;
        } else {
          std::string val = ok ? data_[key] : std::string();
          lk.unlock();
          uint8_t st = ok ? 0 : 1;
          if (!write_full(fd, &st, 1)) break;
          if (ok) {
            uint32_t vlen = static_cast<uint32_t>(val.size());
            if (!write_full(fd, &vlen, 4)) break;
            if (vlen && !write_full(fd, val.data(), vlen)) break;
          }
        }
      } else if (op == kAdd) {
        int64_t delta;
        if (!read_full(fd, &delta, 8)) break;
        int64_t cur = 0;
        {
          std::lock_guard<std::mutex> g(mu_);
          auto it = data_.find(key);
          if (it != data_.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string v(8, '\0');
          std::memcpy(v.data(), &cur, 8);
          data_[key] = std::move(v);
        }
        cv_.notify_all();
        uint8_t st = 0;
        if (!write_full(fd, &st, 1) || !write_full(fd, &cur, 8)) break;
      } else if (op == kPing) {
        uint8_t st = 0;
        if (!write_full(fd, &st, 1)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::vector<std::thread> workers_;
  std::vector<int> client_fds_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
};

class StoreClient {
 public:
  bool Connect(const char* host, int port, int timeout_ms) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (::getaddrinfo(host, port_s.c_str(), &hints, &res) != 0 || !res)
      return false;
    // retry until the server comes up or the deadline passes (ranks race
    // with rank0's bind — the reference client retries the same way)
    while (std::chrono::steady_clock::now() < deadline) {
      fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd_ >= 0 &&
          ::connect(fd_, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        ::freeaddrinfo(res);
        return true;
      }
      if (fd_ >= 0) ::close(fd_);
      fd_ = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    ::freeaddrinfo(res);
    return false;
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  int Set(const char* key, const uint8_t* val, int len) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = kSet;
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    uint32_t vlen = static_cast<uint32_t>(len);
    if (!(write_full(fd_, &op, 1) && write_full(fd_, &klen, 4) &&
          write_full(fd_, key, klen) && write_full(fd_, &vlen, 4) &&
          (len == 0 || write_full(fd_, val, vlen))))
      return -1;
    uint8_t st;
    return read_full(fd_, &st, 1) && st == 0 ? 0 : -1;
  }

  // returns value length, -1 on timeout, -2 on connection error,
  // -3 - needed_len when buf is too small (value is consumed)
  int Get(const char* key, uint8_t* buf, int buflen, int timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = kGet;
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    int32_t to = timeout_ms;
    if (!(write_full(fd_, &op, 1) && write_full(fd_, &klen, 4) &&
          write_full(fd_, key, klen) && write_full(fd_, &to, 4)))
      return -2;
    uint8_t st;
    if (!read_full(fd_, &st, 1)) return -2;
    if (st != 0) return -1;
    uint32_t vlen;
    if (!read_full(fd_, &vlen, 4)) return -2;
    std::string tmp(vlen, '\0');
    if (vlen && !read_full(fd_, tmp.data(), vlen)) return -2;
    if (static_cast<int>(vlen) > buflen) return -3 - static_cast<int>(vlen);
    std::memcpy(buf, tmp.data(), vlen);
    return static_cast<int>(vlen);
  }

  long long Add(const char* key, long long delta, int* status) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = kAdd;
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    int64_t d = delta;
    *status = -1;
    if (!(write_full(fd_, &op, 1) && write_full(fd_, &klen, 4) &&
          write_full(fd_, key, klen) && write_full(fd_, &d, 8)))
      return 0;
    uint8_t st;
    int64_t out;
    if (!read_full(fd_, &st, 1) || st != 0 || !read_full(fd_, &out, 8))
      return 0;
    *status = 0;
    return out;
  }

  int Wait(const char* key, int timeout_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t op = kWait;
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    int32_t to = timeout_ms;
    if (!(write_full(fd_, &op, 1) && write_full(fd_, &klen, 4) &&
          write_full(fd_, key, klen) && write_full(fd_, &to, 4)))
      return -2;
    uint8_t st;
    if (!read_full(fd_, &st, 1)) return -2;
    return st == 0 ? 0 : -1;
  }

 private:
  int fd_ = -1;
  std::mutex mu_;  // one request in flight per client handle
};

}  // namespace

extern "C" {

void* pt_tcpstore_server_start(int port) {
  auto* s = new StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pt_tcpstore_server_port(void* h) {
  return static_cast<StoreServer*>(h)->port();
}

void pt_tcpstore_server_stop(void* h) { delete static_cast<StoreServer*>(h); }

void* pt_tcpstore_connect(const char* host, int port, int timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pt_tcpstore_close(void* h) { delete static_cast<StoreClient*>(h); }

int pt_tcpstore_set(void* h, const char* key, const uint8_t* val, int len) {
  return static_cast<StoreClient*>(h)->Set(key, val, len);
}

int pt_tcpstore_get(void* h, const char* key, uint8_t* buf, int buflen,
                    int timeout_ms) {
  return static_cast<StoreClient*>(h)->Get(key, buf, buflen, timeout_ms);
}

long long pt_tcpstore_add(void* h, const char* key, long long delta,
                          int* status) {
  return static_cast<StoreClient*>(h)->Add(key, delta, status);
}

int pt_tcpstore_wait(void* h, const char* key, int timeout_ms) {
  return static_cast<StoreClient*>(h)->Wait(key, timeout_ms);
}

}  // extern "C"
