"""Gradient clipping (reference ``python/paddle/fluid/clip.py``:
ClipGradByValue/ByNorm/ByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max), stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor(g._value * scale, stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference fluid/clip.py ClipGradByGlobalNorm. In hybrid-parallel mode the
    squared-norm is reduced across model-parallel groups by the
    HybridParallelClipGrad wrapper (paddle_tpu.distributed.fleet)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        sq = [
            jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            for p, g in params_grads
            if g is not None and getattr(p, "need_clip", True)
        ]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-6), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor(g._value * scale.astype(g._value.dtype), stop_gradient=True)))
        return out
