"""VisualDL-compatible scalar logging (reference: the ``visualdl`` package
used by ``hapi/callbacks.py VisualDL`` and ``platform/monitor.h`` stat
registry). Records land in JSONL files — one line per datum — so any
dashboard (or plain pandas) can read them without a VisualDL install."""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogWriter", "get_monitor", "Monitor"]


class LogWriter:
    """``LogWriter(logdir).add_scalar(tag, value, step)`` (VisualDL API)."""

    def __init__(self, logdir, max_queue=20, flush_secs=120, filename_suffix="",
                 display_name="", file_name="", **kwargs):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        name = file_name or f"vdlrecords.{int(time.time())}{filename_suffix}.jsonl"
        self._path = os.path.join(logdir, name)
        self._f = open(self._path, "a", buffering=1)

    @property
    def file_name(self):
        return self._path

    def add_scalar(self, tag, value, step=None, walltime=None):
        self._f.write(json.dumps({
            "tag": tag, "value": float(value),
            "step": int(step) if step is not None else None,
            "walltime": walltime or time.time(),
        }) + "\n")

    def add_text(self, tag, text_string, step=None):
        self._f.write(json.dumps({
            "tag": tag, "text": str(text_string),
            "step": int(step) if step is not None else None,
        }) + "\n")

    def add_hparams(self, hparams_dict, metrics_list=None):
        self._f.write(json.dumps({"hparams": hparams_dict,
                                  "metrics": metrics_list or []}) + "\n")

    def flush(self):
        self._f.flush()

    def close(self):
        try:
            self._f.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Monitor:
    """Host-side stat registry (reference ``platform/monitor.h``)."""

    def __init__(self):
        self._stats = {}

    def add(self, name, value):
        s = self._stats.setdefault(name, {"count": 0, "sum": 0.0,
                                          "min": float("inf"),
                                          "max": float("-inf")})
        v = float(value)
        s["count"] += 1
        s["sum"] += v
        s["min"] = min(s["min"], v)
        s["max"] = max(s["max"], v)

    def get(self, name):
        return dict(self._stats.get(name, {}))

    def names(self):
        return sorted(self._stats)

    def reset(self, name=None):
        if name is None:
            self._stats.clear()
        else:
            self._stats.pop(name, None)


_MONITOR = Monitor()


def get_monitor():
    return _MONITOR
