"""paddle.utils (reference ``python/paddle/utils/``)."""
from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .log_writer import LogWriter, Monitor, get_monitor  # noqa: F401
import functools as _functools
import importlib as _importlib
import warnings as _warnings


def deprecated(update_to="", since="", reason="", level=0):
    """reference ``utils/deprecated.py``: decorator emitting a
    DeprecationWarning (level 2 raises)."""

    def deco(fn):
        msg = (f"API {fn.__module__}.{fn.__name__} is deprecated"
               + (f" since {since}" if since else "")
               + (f", use {update_to} instead" if update_to else "")
               + (f". Reason: {reason}" if reason else ""))

        @_functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(msg)
            if level == 1 or level == 0:
                _warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def require_version(min_version, max_version=None):
    """reference ``utils/install_check require_version``: check the
    installed framework version (this build reports its own)."""
    from ..version import full_version

    def parse(v):
        import re as _re

        out = []
        for part in str(v).split(".")[:3]:
            m = _re.match(r"\d+", part)
            out.append(int(m.group()) if m else 0)
        while len(out) < 3:
            out.append(0)
        return tuple(out)

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > maximum {max_version}")


def run_check():
    """reference ``utils/install_check.run_check``: a tiny end-to-end
    train step proving the install works on this device."""
    import numpy as _np

    from .. import nn, optimizer, to_tensor

    from . import unique_name

    with unique_name.guard():
        lin = nn.Linear(4, 4)
        opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        x = to_tensor(_np.ones((2, 4), _np.float32))
        loss = lin(x).square().mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    print("PaddlePaddle(TPU build) is installed successfully!")


def try_import(module_name, err_msg=None):
    """reference ``utils/lazy_import.try_import``."""
    try:
        return _importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Failed to import {module_name!r}; install it first.")


_WARNED_ONCE = set()


def warn_once(key, msg, stacklevel=3):
    """Emit ``msg`` as a UserWarning at most once per process for ``key``
    (shared one-shot-warning helper for accepted-but-inert knobs and
    degraded fallbacks — inference Config, ZeRO offload, PTQ skips)."""
    if key not in _WARNED_ONCE:
        _WARNED_ONCE.add(key)
        import warnings

        warnings.warn(msg, stacklevel=stacklevel)
