"""paddle.utils (reference ``python/paddle/utils/``)."""
from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .log_writer import LogWriter, Monitor, get_monitor  # noqa: F401
