"""Stable auto-generated names (reference ``python/paddle/utils/unique_name.py``,
backed by ``fluid/unique_name.py`` UniqueNameGenerator).

Parameters get deterministic names ("param_0", "linear_1.w_0"-style prefixes)
at creation so optimizer state_dict keys are portable across processes —
model construction order, not id(), defines the key.
"""
from __future__ import annotations

import contextlib

__all__ = ["generate", "guard", "switch"]


class UniqueNameGenerator:
    def __init__(self):
        self._ids = {}

    def __call__(self, key: str) -> str:
        n = self._ids.get(key, 0)
        self._ids[key] = n + 1
        return f"{key}_{n}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator=None):
    """Replace the global generator, returning the old one."""
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh generator (reference unique_name.guard) so name counters
    restart — used by tests constructing twin models that must share keys."""
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
