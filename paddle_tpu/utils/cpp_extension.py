"""Custom-op extension point.

Reference: ``paddle/fluid/framework/custom_operator.cc`` + ``phi/api/ext``
(user out-of-tree C++ ops registered at runtime) and
``python/paddle/utils/cpp_extension``.

TPU-native redesign: a custom op is a jax-traceable forward (python; may
itself wrap an XLA custom_call / Pallas kernel / ``jax.pure_callback`` into
native code) plus an optional backward. :func:`register_custom_op` installs
it in the global op registry with full autograd/jit/static-recording
support — the role the reference's REGISTER_OP + dynamic library loading
plays, without the ABI surface XLA already owns.
"""
from __future__ import annotations

import jax

from ..ops.dispatch import OP_REGISTRY, op

__all__ = ["register_custom_op", "CustomOpError"]


class CustomOpError(RuntimeError):
    pass


def register_custom_op(name, forward, backward=None, num_inputs=None):
    """Register ``name`` as a framework op.

    Args:
        forward: jax-level function ``(*arrays, **attrs) -> array(s)``.
        backward: optional ``(residuals, grads) -> input-cotangents`` pair
            given as ``(save_fn, grad_fn)`` where ``save_fn(*arrays) ->
            (out, residuals)``; when omitted, autodiff falls back to
            ``jax.vjp`` of ``forward``.
        num_inputs: arity check (optional).

    Returns the callable op (also retrievable via the registry).
    """
    if name in OP_REGISTRY:
        raise CustomOpError(f"op {name!r} is already registered")
    fwd = forward
    if backward is not None:
        save_fn, grad_fn = backward
        fwd = jax.custom_vjp(forward)
        fwd.defvjp(save_fn, grad_fn)

    wrapper = op(name)(fwd)

    if num_inputs is not None:
        inner = wrapper

        def checked(*args, **kwargs):
            n_pos = len(args)
            if n_pos != num_inputs:
                raise CustomOpError(
                    f"custom op {name!r} expects {num_inputs} inputs, got {n_pos}")
            return inner(*args, **kwargs)

        checked.op_name = name
        OP_REGISTRY[name] = checked
        wrapper = checked
    return wrapper
