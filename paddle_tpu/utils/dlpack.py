"""DLPack interop (reference ``paddle/fluid/framework/dlpack_tensor.cc`` +
``python/paddle/utils/dlpack.py``): zero-copy tensor exchange with other
frameworks via the DLPack protocol, delegated to jax's implementation."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor as a DLPack capsule (reference ``utils/dlpack.py
    to_dlpack``). The source array must stay alive while the capsule is."""
    if not isinstance(x, Tensor):
        raise TypeError(f"to_dlpack expects a paddle Tensor, got {type(x)}")
    return x._value.__dlpack__()


def from_dlpack(dlpack):
    """Import a DLPack capsule or any object with ``__dlpack__`` (torch/numpy
    arrays included) as a Tensor."""
    return Tensor(jnp.from_dlpack(dlpack))
