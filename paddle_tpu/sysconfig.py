"""paddle.sysconfig (reference ``python/paddle/sysconfig.py``)."""
import os

__all__ = ["get_include", "get_lib"]

_HERE = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """C headers dir (native core sources double as the include surface)."""
    return os.path.join(_HERE, "core", "native")


def get_lib():
    """Directory holding the compiled native runtime library."""
    return os.path.join(_HERE, "core", "_build")
