"""Driver benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline
compares against the previous round's BENCH_r*.json when present, else 1.0.

Measurement protocol (warmup/donated-state chain/fence-on-last-loss) and
the chip-peak table live in tools/bench_common.py, shared with the
ResNet-50 and BERT-large benchmarks. Batches are HOST numpy arrays staged
through io.DeviceLoader (double-buffered async host→device prefetch) and
the step donates its input buffers (CompiledStep donate_inputs=True) — the
measured number includes the production input pipeline, with transfer
overlapped and batch HBM recycled into the step's temporaries.
"""
from __future__ import annotations

import glob
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools"))
from bench_common import (  # noqa: E402
    device_peak,
    measure_steps,
    retry,
    telemetry_block,
)


def main():
    retry(_run)


def _run():
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    # GPT-2 small (124M); bf16 compute + fp32 master weights on TPU.
    # batch 24 is the measured per-chip MFU optimum on v5e (b16: 119.0k,
    # b24: 120.1k, b32: 110.3k tok/s — bigger batches start losing to HBM
    # pressure against the fused-CE transient)
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
            max_position_embeddings=1024, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 24, 1024
    else:  # smoke-scale for CPU runs
        cfg = GPTConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 4, 64

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        # keep layernorms fp32 for stability
        for name, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=on_tpu
    )

    def train_step(ids, labels):
        # fused LM-head matmul + softmax-CE: the [b*s, vocab] logits tensor
        # never materializes in HBM (ops/fused.py)
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # donate_inputs: every batch below is a single-use staged array, so its
    # HBM is recycled into the step's temporaries (attacks the "b32 loses
    # to HBM pressure" ceiling at larger batch sizes)
    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True,
                        donate_inputs=True)

    iters = 10 if on_tpu else 5
    # distinct, time-seeded data per step: the remote execution layer caches
    # results across processes keyed on (executable, inputs), so repeated
    # fixed-seed runs would replay cached results and inflate the number
    rng = np.random.RandomState(time.time_ns() % (2**31))
    batches = []
    for _ in range(3 + iters):
        # host numpy, staged by measure_steps' DeviceLoader; labels are a
        # separate buffer (ids are donated — no aliased donation)
        a = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        batches.append((a, a.copy()))

    total, _ = measure_steps(step, batches, iters)
    tokens_per_sec = batch * seq * iters / total
    # phase attribution for the perf trajectory: steps/s, data-wait
    # fraction, compile/recompile counts, DeviceLoader prefetch stats
    telemetry = telemetry_block(total, iters)

    # Achieved MFU against the chip's bf16 peak by device_kind. Preferred
    # FLOP count: XLA's own cost analysis of the compiled step (harvested
    # by profiler.devprof at first compile — includes remat recompute, the
    # honest hardware-utilization number). Fallback: the standard
    # 6*N_matmul + 12*L*H*s flops/token convention (fwd+bwd; matmul params
    # = decoder blocks + tied head, embedding lookups excluded).
    from paddle_tpu.profiler import devprof

    kind, peak = device_peak()
    rep = devprof.get_report("train_step") or devprof.last_report()
    mfu = mfu_source = None
    # mfu only when the chip's bf16 peak is known — never a guessed peak
    if peak:
        if rep is not None and rep.flops:
            mfu = (rep.flops * iters / total) / peak
            mfu_source = "xla_cost_analysis"
        else:
            h_, l_, v_, s_ = (cfg.hidden_size, cfg.num_layers,
                              cfg.vocab_size, seq)
            n_matmul = l_ * 12 * h_ * h_ + v_ * h_
            flops_per_token = 6 * n_matmul + 12 * l_ * h_ * s_
            mfu = tokens_per_sec * flops_per_token / peak
            mfu_source = "analytic"

    prev = 0.0
    for f in sorted(glob.glob("BENCH_r*.json")):
        try:
            d = json.load(open(f))
            # the driver wraps our line under "parsed" in BENCH_r*.json
            if isinstance(d.get("parsed"), dict):
                d = d["parsed"]
            if d.get("unit") == "tokens/sec/chip":
                prev = float(d.get("value", 0.0))
        except Exception:
            pass
    vs = tokens_per_sec / prev if prev > 0 else 1.0

    print(json.dumps({
        "metric": f"gpt2-124M train throughput ({backend})" if on_tpu
                  else f"gpt-smoke train throughput ({backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_source": mfu_source,
        "device_kind": kind,
        "telemetry": telemetry,
    }))


if __name__ == "__main__":
    main()
