"""Driver benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline
compares against the previous round's BENCH_r*.json when present, else 1.0.

Measurement protocol (warmup/donated-state chain/fence-on-last-loss) and
the chip-peak table live in tools/bench_common.py, shared with the
ResNet-50 and BERT-large benchmarks. Batches are HOST numpy arrays staged
through io.DeviceLoader (double-buffered async host→device prefetch) and
the step donates its input buffers (CompiledStep donate_inputs=True) — the
measured number includes the production input pipeline, with transfer
overlapped and batch HBM recycled into the step's temporaries.

``--dp N --zero`` switches to the comm-optimized data-parallel benchmark
(distributed/sharding/zero.py): the smoke GPT under a pure-dp mesh with
the ZeRO sharded weight update, reporting tokens/sec, comm_fraction,
per-replica optimizer-state bytes vs the replicated-Adam baseline, and
(with ``--parity``) the loss-parity check the CI gate asserts — exact for
ZeRO alone, rtol-gated for ``--int8`` (quantized param all-gather with
error feedback). On hosts without ``N`` devices the dp mesh is virtualized
over XLA:CPU (``xla_force_host_platform_device_count``).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tools"))
from bench_common import (  # noqa: E402
    device_peak,
    measure_steps,
    retry,
    telemetry_block,
)

#: int8 + error feedback loss-parity gate (max relative deviation from the
#: replicated-Adam curve over the smoke run)
INT8_PARITY_RTOL = 2e-2

#: fp32 ZeRO is exact in math (sharding constraints move data, never
#: values) and typically bitwise — but XLA:CPU's thread-pool reduction
#: scheduling can reorder an all-reduce between compiles, wiggling the
#: last ulp. Gate at last-ulp scale; the emitted doc still records the
#: per-run ``bitwise`` flag.
FP32_PARITY_RTOL = 1e-5


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dp", type=int, default=None,
                    help="data-parallel ways; enables the multichip bench")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO sharded weight update over the dp axis")
    ap.add_argument("--int8", action="store_true",
                    help="int8 + error-feedback param all-gather")
    ap.add_argument("--parity", action="store_true",
                    help="assert loss parity vs the replicated-Adam "
                         "baseline (bitwise for fp32 ZeRO, rtol for int8)")
    ap.add_argument("--artifact", default=None,
                    help="also write the result JSON to this path")
    args = ap.parse_args(argv)
    if args.dp is None:
        retry(_run)
        return
    # the dp mesh needs the devices BEFORE jax initializes its backend
    if os.environ.get("PADDLE_TPU_HW_TESTS") != "1":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.dp}")
    retry(lambda: _run_zero(args))


def _run():
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    # GPT-2 small (124M); bf16 compute + fp32 master weights on TPU.
    # batch 24 is the measured per-chip MFU optimum on v5e (b16: 119.0k,
    # b24: 120.1k, b32: 110.3k tok/s — bigger batches start losing to HBM
    # pressure against the fused-CE transient)
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
            max_position_embeddings=1024, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 24, 1024
    else:  # smoke-scale for CPU runs
        cfg = GPTConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 4, 64

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        # keep layernorms fp32 for stability
        for name, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=on_tpu
    )

    def train_step(ids, labels):
        # fused LM-head matmul + softmax-CE: the [b*s, vocab] logits tensor
        # never materializes in HBM (ops/fused.py)
        loss = model.loss(ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    # donate_inputs: every batch below is a single-use staged array, so its
    # HBM is recycled into the step's temporaries (attacks the "b32 loses
    # to HBM pressure" ceiling at larger batch sizes)
    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True,
                        donate_inputs=True)

    iters = 10 if on_tpu else 5
    # distinct, time-seeded data per step: the remote execution layer caches
    # results across processes keyed on (executable, inputs), so repeated
    # fixed-seed runs would replay cached results and inflate the number
    rng = np.random.RandomState(time.time_ns() % (2**31))
    batches = []
    for _ in range(3 + iters):
        # host numpy, staged by measure_steps' DeviceLoader; labels are a
        # separate buffer (ids are donated — no aliased donation)
        a = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64)
        batches.append((a, a.copy()))

    total, _ = measure_steps(step, batches, iters)
    tokens_per_sec = batch * seq * iters / total
    # phase attribution for the perf trajectory: steps/s, data-wait
    # fraction, compile/recompile counts, DeviceLoader prefetch stats
    telemetry = telemetry_block(total, iters)

    # Achieved MFU against the chip's bf16 peak by device_kind. Preferred
    # FLOP count: XLA's own cost analysis of the compiled step (harvested
    # by profiler.devprof at first compile — includes remat recompute, the
    # honest hardware-utilization number). Fallback: the standard
    # 6*N_matmul + 12*L*H*s flops/token convention (fwd+bwd; matmul params
    # = decoder blocks + tied head, embedding lookups excluded).
    from paddle_tpu.profiler import devprof

    kind, peak = device_peak()
    rep = devprof.get_report("train_step") or devprof.last_report()
    mfu = mfu_source = None
    # mfu only when the chip's bf16 peak is known — never a guessed peak
    if peak:
        if rep is not None and rep.flops:
            mfu = (rep.flops * iters / total) / peak
            mfu_source = "xla_cost_analysis"
        else:
            h_, l_, v_, s_ = (cfg.hidden_size, cfg.num_layers,
                              cfg.vocab_size, seq)
            n_matmul = l_ * 12 * h_ * h_ + v_ * h_
            flops_per_token = 6 * n_matmul + 12 * l_ * h_ * s_
            mfu = tokens_per_sec * flops_per_token / peak
            mfu_source = "analytic"

    prev = 0.0
    for f in sorted(glob.glob("BENCH_r*.json")):
        try:
            d = json.load(open(f))
            # the driver wraps our line under "parsed" in BENCH_r*.json
            if isinstance(d.get("parsed"), dict):
                d = d["parsed"]
            if d.get("unit") == "tokens/sec/chip":
                prev = float(d.get("value", 0.0))
        except Exception:
            pass
    vs = tokens_per_sec / prev if prev > 0 else 1.0

    print(json.dumps({
        "metric": f"gpt2-124M train throughput ({backend})" if on_tpu
                  else f"gpt-smoke train throughput ({backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_source": mfu_source,
        "device_kind": kind,
        "telemetry": telemetry,
    }))


def _acc_bytes(opt):
    """Per-replica optimizer-state bytes: local shard sizes when sharded."""
    total = 0
    for store in opt._accumulators.values():
        for v in store.values():
            if hasattr(v, "sharding") and hasattr(v.sharding, "shard_shape"):
                shape = v.sharding.shard_shape(v.shape)
            else:
                shape = v.shape
            total += int(np.prod(shape)) * v.dtype.itemsize
    return total


def _run_zero(args):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    backend = jax.default_backend()
    if jax.device_count() < args.dp:
        raise SystemExit(f"--dp {args.dp} needs {args.dp} devices; "
                         f"found {jax.device_count()} ({backend})")

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.sharding import ShardedOptimizer
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.utils import unique_name

    cfg = GPTConfig(
        vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
        max_position_embeddings=128, hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    batch, seq, iters, k_parity = 4 * args.dp, 64, 5, 5
    mesh = build_mesh({"dp": args.dp})
    quantize = "int8" if args.int8 else None

    def build(zero):
        with unique_name.guard():
            paddle.seed(0)
            model = GPTForCausalLM(cfg)
        rep = NamedSharding(mesh, P())
        for p in model.parameters():
            p._value = jax.device_put(p._value, rep)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        stepper = (ShardedOptimizer(opt, axis="dp", mesh=mesh,
                                    quantize=quantize) if zero else opt)

        def train_step(ids, labels):
            loss = model.loss(ids, labels)
            loss.backward()
            stepper.step()
            stepper.clear_grad()
            return loss

        train_step.__name__ = ("zero_train_step" if zero
                               else "dp_train_step")
        # stateful threads the INNER optimizer: the wrapper holds no
        # arrays of its own (ef residuals live in the inner accumulators)
        step = CompiledStep(train_step, stateful=[model, opt],
                            donate_state=True)
        return step, opt

    def batches_for(rng, n):
        sh = NamedSharding(mesh, P("dp", None))
        out = []
        for _ in range(n):
            a = rng.randint(0, cfg.vocab_size, (batch, seq))
            ids = jax.device_put(np.asarray(a, np.int32), sh)
            out.append((Tensor(ids), Tensor(ids.copy())))
        return out

    # distinct seeds per invocation (remote result-cache workaround) but
    # SHARED between the baseline and ZeRO runs — parity needs identical
    # data streams
    data_seed = time.time_ns() % (2**31)

    # -- replicated-Adam baseline (parity reference + comm/state baseline)
    base_step, base_opt = build(zero=False)
    base_parity = [float(np.asarray(base_step(*b)._value))
                   for b in batches_for(np.random.RandomState(data_seed),
                                        k_parity)]
    sample = batches_for(np.random.RandomState(data_seed + 2), 1)[0]
    # the step compiled during the parity loop (telemetry off) — harvest
    # the device ground truth explicitly so telemetry_block's comm stats
    # (comm_fraction, comm.bytes.dp) have a report to fall back on
    base_step.device_report(*sample)
    base_total, _ = measure_steps(
        base_step, batches_for(np.random.RandomState(data_seed + 1),
                               3 + iters), iters, prefetch=0)
    base_tok = batch * seq * iters / base_total
    base_telemetry = telemetry_block(base_total, iters)
    base_state = _acc_bytes(base_opt)

    # -- ZeRO run
    zero_step, zero_opt = build(zero=True)
    zero_parity = [float(np.asarray(zero_step(*b)._value))
                   for b in batches_for(np.random.RandomState(data_seed),
                                        k_parity)]
    zero_step.device_report(*sample)
    zero_total, _ = measure_steps(
        zero_step, batches_for(np.random.RandomState(data_seed + 1),
                               3 + iters), iters, prefetch=0)
    zero_tok = batch * seq * iters / zero_total
    zero_telemetry = telemetry_block(zero_total, iters)
    zero_state = _acc_bytes(zero_opt)

    max_abs = max(abs(a - b) for a, b in zip(base_parity, zero_parity))
    max_rel = max(abs(a - b) / max(abs(a), 1e-12)
                  for a, b in zip(base_parity, zero_parity))
    bitwise = base_parity == zero_parity
    parity = {
        "steps": k_parity,
        "bitwise": bitwise,
        "max_abs": max_abs,
        "max_rel": max_rel,
        "gate": (f"rtol<{FP32_PARITY_RTOL}" if quantize is None
                 else f"rtol<{INT8_PARITY_RTOL}"),
    }
    if args.parity:
        if quantize is None:
            assert max_rel < FP32_PARITY_RTOL, (
                f"fp32 ZeRO parity drift {max_rel:.3e} exceeds "
                f"{FP32_PARITY_RTOL} vs replicated Adam: "
                f"base={base_parity} zero={zero_parity}")
        else:
            assert max_rel < INT8_PARITY_RTOL, (
                f"int8+EF parity drift {max_rel:.3e} exceeds "
                f"{INT8_PARITY_RTOL}: base={base_parity} "
                f"zero={zero_parity}")

    doc = {
        "metric": f"gpt-smoke zero-dp{args.dp}"
                  f"{'-int8' if args.int8 else ''} train throughput "
                  f"({backend})",
        "value": round(zero_tok, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(zero_tok / base_tok, 3) if base_tok else 1.0,
        "dp": args.dp,
        "zero": True,
        "int8": bool(args.int8),
        "parity": parity,
        "state_bytes": {
            "replicated": base_state,
            "sharded": zero_state,
            "ratio": round(base_state / zero_state, 3) if zero_state
                     else None,
        },
        "baseline": {
            "value": round(base_tok, 1),
            "comm_fraction": base_telemetry.get("comm_fraction"),
            "comm_bytes_by_axis": base_telemetry.get("comm_bytes_by_axis"),
        },
        "telemetry": zero_telemetry,
    }
    line = json.dumps(doc)
    print(line)
    if args.artifact:
        with open(args.artifact, "w") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()
