"""Driver benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md) — vs_baseline
compares against the previous round's BENCH_r*.json when present, else 1.0.
"""
from __future__ import annotations

import glob
import json
import os
import sys
import time

import numpy as np


def main():
    """Retry wrapper: the remote-compile tunnel to the TPU terminal can drop
    mid-run (round 1 lost its number to exactly that); transient infra
    failures get 3 attempts before the benchmark reports failure."""
    last = None
    for attempt in range(3):
        if attempt:
            time.sleep(5.0 * attempt)
        try:
            return _run()
        except Exception as e:  # noqa: BLE001 - retry any runtime failure
            last = e
            print(f"bench attempt {attempt + 1} failed: {e!r}", file=sys.stderr)
            try:
                import jax

                jax.clear_caches()
            except Exception:
                pass
    raise last


def _run():
    import jax

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.jit.functionalize import CompiledStep
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    # GPT-2 small (124M); bf16 compute + fp32 master weights on TPU
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
            max_position_embeddings=1024, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 16, 1024
    else:  # smoke-scale for CPU runs
        cfg = GPTConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            max_position_embeddings=128, hidden_dropout=0.0, attention_dropout=0.0,
        )
        batch, seq = 4, 64

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.to(dtype="bfloat16")
        # keep layernorms fp32 for stability
        for name, sub in model.named_sublayers():
            if type(sub).__name__ == "LayerNorm":
                sub.to(dtype="float32")
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-4, parameters=model.parameters(), multi_precision=on_tpu
    )

    def train_step(ids, labels):
        logits = model(ids)
        loss = F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]).astype("float32"),
            labels.reshape([-1, 1]),
        ).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = CompiledStep(train_step, stateful=[model, opt], donate_state=True)

    iters = 10 if on_tpu else 5
    # distinct, time-seeded data per step: the remote execution layer caches
    # results across processes keyed on (executable, inputs), so repeated
    # fixed-seed runs would replay cached results and inflate the number
    rng = np.random.RandomState(time.time_ns() % (2**31))
    batches = [
        Tensor(rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int64))
        for _ in range(3 + iters)
    ]

    # warmup (compile)
    for i in range(3):
        loss = step(batches[i], batches[i])
    loss._value.block_until_ready()

    # per-step fence: materialize each loss on the host.  Through the
    # remote-TPU tunnel block_until_ready() can return before the dependent
    # chain has executed (and deep async queues dispatch slower than synced
    # steps), so fetching the value is the only honest fence.  Median step
    # time is robust to transient tunnel hiccups.
    times = []
    final_loss = None
    for i in range(iters):
        b = batches[3 + i]
        t0 = time.perf_counter()
        loss = step(b, b)
        final_loss = float(np.asarray(loss._value))
        times.append(time.perf_counter() - t0)
    assert np.isfinite(final_loss), f"bench loss not finite: {final_loss}"

    tokens_per_sec = batch * seq / float(np.median(times))

    prev = 0.0
    for f in sorted(glob.glob("BENCH_r*.json")):
        try:
            d = json.load(open(f))
            if d.get("unit") == "tokens/sec/chip":
                prev = float(d.get("value", 0.0))
        except Exception:
            pass
    vs = tokens_per_sec / prev if prev > 0 else 1.0

    print(json.dumps({
        "metric": f"gpt2-124M train throughput ({backend})" if on_tpu
                  else f"gpt-smoke train throughput ({backend})",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
